"""Cross-TMS HTLC atomic swap (BASELINE config 4).

Two INDEPENDENT token management services — TMS-A runs the fabtoken driver
with USD, TMS-B runs the zkatdlog (ZK privacy) driver with EUR — complete
an atomic swap through hash-locked scripts sharing one preimage, exactly
the reference's interop flow (integration/token/interop/ suites; htlc
script semantics from token/services/interop/htlc):

  1. alice locks 100 USD on A  (script: alice -> bob,   hash H, deadline T_A)
  2. bob   locks  77 EUR on B  (script: bob -> alice,   hash H, T_B < T_A)
  3. alice claims the EUR on B, REVEALING the preimage on B's ledger
  4. bob reads the preimage from B's ledger state and claims the USD on A

Also covers the abort path: bob never locks, alice reclaims after her
deadline, and nothing moves on B.
"""

import hashlib
import time

import pytest

from fabric_token_sdk_tpu.core import fabtoken, zkatdlog
from fabric_token_sdk_tpu.core.fabtoken.actions import (IssueAction, Output,
                                                        TransferAction)
from fabric_token_sdk_tpu.core.zkatdlog.actions import (ActionInput,
                                                        IssueAction as ZkIssue,
                                                        Token,
                                                        TransferAction as ZkTransfer)
from fabric_token_sdk_tpu.crypto import setup as zk_setup
from fabric_token_sdk_tpu.crypto import issue_proof, token_commit, transfer_proof
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import (X509Verifier,
                                                         new_signing_identity)
from fabric_token_sdk_tpu.services.interop.htlc import (ClaimSignature,
                                                        HashInfo, Script,
                                                        claim_key, lock_key,
                                                        lock_value)
from fabric_token_sdk_tpu.services.interop.htlc import (
    script_verifier_resolver)
from fabric_token_sdk_tpu.services.network.rws import KeyTranslator
from fabric_token_sdk_tpu.services.network.tcc import (MemoryLedger,
                                                       TokenChaincode)
from fabric_token_sdk_tpu.token.model import ID

BIT_LENGTH = 16


def _deserializer():
    return Deserializer(extra_owner_resolvers=[
        script_verifier_resolver(
            lambda ident: X509Verifier.from_identity(ident))])


@pytest.fixture
def swap_world():
    """Two TMSes + the four parties. alice/bob exist on BOTH networks."""
    issuer_a, auditor_a = new_signing_identity(), new_signing_identity()
    issuer_b, auditor_b = new_signing_identity(), new_signing_identity()
    alice, bob = new_signing_identity(), new_signing_identity()

    pp_a = fabtoken.setup(64)
    pp_a.issuer_ids = [issuer_a.identity]
    pp_a.auditor = bytes(auditor_a.identity)
    ledger_a = MemoryLedger()
    cc_a = TokenChaincode(fabtoken.new_validator(pp_a, _deserializer()),
                          ledger_a, pp_a.serialize())

    pp_b = zk_setup.setup(BIT_LENGTH)
    pp_b.issuer_ids = [issuer_b.identity]
    pp_b.auditor = bytes(auditor_b.identity)
    ledger_b = MemoryLedger()
    cc_b = TokenChaincode(
        zkatdlog.new_validator(pp_b, _deserializer(), device=False),
        ledger_b, pp_b.serialize())

    return dict(pp_a=pp_a, cc_a=cc_a, ledger_a=ledger_a, issuer_a=issuer_a,
                auditor_a=auditor_a, pp_b=pp_b, cc_b=cc_b,
                ledger_b=ledger_b, issuer_b=issuer_b, auditor_b=auditor_b,
                alice=alice, bob=bob)


def _submit_a(w, tx_id, issues=(), transfers=(), sigs=()):
    req = TokenRequest(issues=[a.serialize() for a in issues],
                       transfers=[a.serialize() for a in transfers])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [w["auditor_a"].sign(msg)]
    req.signatures = [s(msg) if callable(s) else s for s in sigs]
    return w["cc_a"].process_request(tx_id, req.to_bytes()), msg


def _submit_b(w, tx_id, issues=(), transfers=(), sigs=(), raw_sigs=None):
    req = TokenRequest(issues=[a.serialize() for a in issues],
                       transfers=[a.serialize() for a in transfers])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [w["auditor_b"].sign(msg)]
    if raw_sigs is not None:
        req.signatures = raw_sigs(msg)
    else:
        req.signatures = [s(msg) if callable(s) else s for s in sigs]
    return w["cc_b"].process_request(tx_id, req.to_bytes()), msg


def _issue_usd_to_alice(w):
    issue = IssueAction(issuer=w["issuer_a"].identity,
                        outputs=[Output(bytes(w["alice"].identity), "USD",
                                        "0x64")])
    ev, _ = _submit_a(w, "a-issue", issues=[issue],
                      sigs=[w["issuer_a"].sign])
    assert ev.status == "VALID", ev.message
    return issue


def _issue_eur_to_bob(w):
    coms, wits = token_commit.get_tokens_with_witness(
        [77], "EUR", w["pp_b"].pedersen_generators)
    proof = issue_proof.issue_prove([x.as_tuple() for x in wits], coms,
                                   w["pp_b"])
    issue = ZkIssue(issuer=w["issuer_b"].identity,
                    outputs=[Token(bytes(w["bob"].identity), coms[0])],
                    proof=proof)
    ev, _ = _submit_b(w, "b-issue", issues=[issue],
                      sigs=[w["issuer_b"].sign])
    assert ev.status == "VALID", ev.message
    return issue, wits


def _swap_scripts(w, preimage: bytes):
    image = hashlib.sha256(preimage).digest().hex().encode()
    now = time.time()
    # alice's lock on A expires LAST: bob must have time to claim with the
    # preimage alice reveals on B
    script_a = Script(sender=bytes(w["alice"].identity),
                      recipient=bytes(w["bob"].identity),
                      deadline=now + 7200, hash_info=HashInfo(hash=image))
    script_b = Script(sender=bytes(w["bob"].identity),
                      recipient=bytes(w["alice"].identity),
                      deadline=now + 3600, hash_info=HashInfo(hash=image))
    return image, script_a, script_b


def test_cross_tms_atomic_swap(swap_world):
    w = swap_world
    alice, bob = w["alice"], w["bob"]
    preimage = b"cross-tms-swap-secret"
    image, script_a, script_b = _swap_scripts(w, preimage)

    usd_issue = _issue_usd_to_alice(w)
    eur_issue, eur_wits = _issue_eur_to_bob(w)

    # 1. alice locks 100 USD on TMS-A under script_a
    lock_a = TransferAction(
        inputs=[ID("a-issue", 0)],
        input_tokens=[usd_issue.outputs[0]],
        outputs=[Output(bytes(script_a.to_owner()), "USD", "0x64")],
        metadata={lock_key(image): lock_value(image)})
    ev, _ = _submit_a(w, "a-lock", transfers=[lock_a], sigs=[alice.sign])
    assert ev.status == "VALID", ev.message

    # 2. bob sees the lock on A and locks 77 EUR on TMS-B under script_b
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [77], "EUR", w["pp_b"].pedersen_generators)
    tproof = transfer_proof.transfer_prove(
        [x.as_tuple() for x in eur_wits], [x.as_tuple() for x in out_wits],
        [eur_issue.outputs[0].data], out_coms, w["pp_b"])
    lock_b = ZkTransfer(
        inputs=[ActionInput(id=ID("b-issue", 0),
                            token=eur_issue.outputs[0])],
        outputs=[Token(bytes(script_b.to_owner()), out_coms[0])],
        proof=tproof,
        metadata={lock_key(image): lock_value(image)})
    ev, _ = _submit_b(w, "b-lock", transfers=[lock_b], sigs=[bob.sign])
    assert ev.status == "VALID", ev.message

    # 3. alice claims the EUR on TMS-B, revealing the preimage
    new_coms, new_wits = token_commit.get_tokens_with_witness(
        [77], "EUR", w["pp_b"].pedersen_generators)
    claim_proof = transfer_proof.transfer_prove(
        [x.as_tuple() for x in out_wits], [x.as_tuple() for x in new_wits],
        out_coms, new_coms, w["pp_b"])
    claim_b = ZkTransfer(
        inputs=[ActionInput(id=ID("b-lock", 0), token=lock_b.outputs[0])],
        outputs=[Token(bytes(alice.identity), new_coms[0])],
        proof=claim_proof,
        metadata={claim_key(image): preimage})
    ev, _ = _submit_b(
        w, "b-claim", transfers=[claim_b],
        raw_sigs=lambda msg: [ClaimSignature(
            recipient_signature=alice.sign(msg),
            preimage=preimage).to_json()])
    assert ev.status == "VALID", ev.message

    # 4. bob learns the preimage FROM B'S LEDGER (the claim wrote it) ...
    keys = KeyTranslator()
    revealed = w["ledger_b"].get_state(
        keys.transfer_metadata_key(claim_key(image).decode()
                                   if isinstance(claim_key(image), bytes)
                                   else claim_key(image)))
    assert revealed == preimage, "preimage must be on B's ledger"

    # ... and claims the USD on TMS-A with it
    claim_a = TransferAction(
        inputs=[ID("a-lock", 0)],
        input_tokens=[lock_a.outputs[0]],
        outputs=[Output(bytes(bob.identity), "USD", "0x64")],
        metadata={claim_key(image): revealed})
    req = TokenRequest(transfers=[claim_a.serialize()])
    msg = req.message_to_sign(b"a-claim")
    req.auditor_signatures = [w["auditor_a"].sign(msg)]
    req.signatures = [ClaimSignature(recipient_signature=bob.sign(msg),
                                     preimage=revealed).to_json()]
    ev = w["cc_a"].process_request("a-claim", req.to_bytes())
    assert ev.status == "VALID", ev.message

    # final state: bob owns the USD output on A; alice owns the EUR on B
    out_a = w["ledger_a"].get_state(keys.output_key("a-claim", 0))
    assert out_a is not None
    from fabric_token_sdk_tpu.core.fabtoken.actions import Output as FabOut

    final = FabOut.deserialize(out_a)
    assert bytes(final.owner) == bytes(bob.identity)
    assert final.quantity == "0x64"
    out_b = w["ledger_b"].get_state(keys.output_key("b-claim", 0))
    assert out_b is not None
    final_b = Token.deserialize(out_b)
    assert bytes(final_b.owner) == bytes(alice.identity)


def test_cross_tms_abort_reclaims_after_deadline(swap_world):
    """bob never locks on B: alice reclaims on A after her deadline and
    TMS-B's ledger never changes."""
    w = swap_world
    alice = w["alice"]
    preimage = b"aborted-swap-secret"
    image = hashlib.sha256(preimage).digest().hex().encode()
    script_a = Script(sender=bytes(alice.identity),
                      recipient=bytes(w["bob"].identity),
                      deadline=time.time() + 1.0,  # expires shortly
                      hash_info=HashInfo(hash=image))

    usd_issue = _issue_usd_to_alice(w)
    lock_a = TransferAction(
        inputs=[ID("a-issue", 0)],
        input_tokens=[usd_issue.outputs[0]],
        outputs=[Output(bytes(script_a.to_owner()), "USD", "0x64")],
        metadata={lock_key(image): lock_value(image)})
    ev, _ = _submit_a(w, "a-lock2", transfers=[lock_a], sigs=[alice.sign])
    assert ev.status == "VALID", ev.message
    time.sleep(1.1)  # bob never locked on B; alice's deadline passes

    state_b_before = dict(w["ledger_b"].state)
    reclaim = TransferAction(
        inputs=[ID("a-lock2", 0)],
        input_tokens=[lock_a.outputs[0]],
        outputs=[Output(bytes(alice.identity), "USD", "0x64")],
        metadata={})
    ev, _ = _submit_a(w, "a-reclaim", transfers=[reclaim],
                      sigs=[alice.sign])
    assert ev.status == "VALID", ev.message
    assert w["ledger_b"].state == state_b_before
