"""Tier-1 wrapper around scripts/check_lazy_bounds.py: no lazy-carry
value may reach a readback boundary (Pallas out_ref store, public
*_mixed fold entry point, or any call site outside ops/) without a
normalization point in the same function.

The standalone script is the pre-commit entry point; this test makes the
invariant part of the suite so a new kernel that forgets its final
normalize_point fails CI, not just the linter nobody ran.
"""

import importlib.util
import pathlib

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
           / "check_lazy_bounds.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_lazy_bounds",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_lazy_boundary_normalizes():
    mod = _load()
    offenders = mod.find_offenders()
    assert not offenders, (
        "lazy-form values escape to a readback boundary without a "
        "normalization point (add tec.normalize_point / tf.normalize "
        f"before the store/return): {offenders}")


def test_linter_sees_the_lazy_boundaries():
    """Guard the guard: the lint must be finding the real boundary set —
    the fused fold kernels and the mixed XLA entry points — or a rename
    would turn it into a silent no-op."""
    mod = _load()
    found = mod.scan_boundaries()
    kernels = [k for k in found if "pallas_fb.py" in k]
    mixed = [k for k in found if k.endswith("_mixed")]
    # _fb_fold_kernel, _fb_msm_kernel + the round-7 lazified var walk
    assert len(kernels) >= 3, found
    assert any(k.endswith("_msm_var_kernel") for k in kernels), found
    # fixed_base_gather_mixed, msm_var_mixed, _multiple_table_mixed, ...
    assert len(mixed) >= 2, found
    # the exact-pass tails consume the lazified MSM interior -> the
    # same-module closure + *_mixed-callee rule must surface them
    # (_exact_mixed_tail_kernel is the round-8 lazified FIXED-base tail)
    for tail in ("_exact_pass_kernel", "_exact_var_tail_kernel",
                 "_k_pass_kernel", "_exact_mixed_tail_kernel"):
        assert any(k.endswith(tail) for k in found), (tail, sorted(found))
    # the prover subsystem's lazy-Z adjusted-sum fold (and the fused
    # type-and-sum program that closes over it) are outside ops/, so
    # the module-boundary rule must surface them as guarded boundaries
    prover = [k for k in found if "/prover/" in k or k.startswith("prover")
              or "prover/transfer.py" in k]
    assert len(prover) >= 2, sorted(found)
    assert any(k.endswith("_adjusted_sum") for k in prover), prover
    # and every one it found is currently clean
    assert all(info["normalizers"] for info in found.values()), found


def test_linter_catches_a_missing_normalize(tmp_path):
    """A synthetic boundary function without a normalizer must trip the
    scan logic (exercise the rule itself, not just today's clean tree)."""
    mod = _load()
    import ast

    bad = ast.parse(
        "def _bad_kernel(x_ref, out_ref):\n"
        "    acc = add_lazy(x_ref[0], x_ref[1])\n"
        "    out_ref[0] = acc\n")
    fn = next(mod._functions(bad))
    assert mod._stores_to_ref(fn)
    calls = mod._called_names(fn)
    assert calls & mod.LAZY_PRODUCERS
    assert not (calls & mod.NORMALIZERS)
