"""Windowed / fixed-base EC kernels vs the host oracle."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import ec, limbs

rng = random.Random(0xF1BED)


def _rand_points(k):
    return [bn254.g1_mul(bn254.G1_GENERATOR, rng.randrange(1, bn254.R))
            for _ in range(k)]


def _host_msm(points, scalars):
    acc = bn254.G1_IDENTITY
    for p, s in zip(points, scalars):
        acc = bn254.g1_add(acc, bn254.g1_mul(p, s))
    return acc


def test_msm_windowed_matches_host():
    B, T = 3, 5
    pts_rows, sc_rows, want = [], [], []
    for b in range(B):
        pts = _rand_points(T)
        scs = [rng.randrange(bn254.R) for _ in range(T)]
        if b == 1:
            scs[2] = 0  # zero scalar
        pts_rows.append(limbs.points_to_projective_limbs(pts))
        sc_rows.append(limbs.scalars_to_limbs(scs))
        want.append(_host_msm(pts, scs))
    out = ec.msm_windowed(jnp.asarray(np.stack(pts_rows)),
                          jnp.asarray(np.stack(sc_rows)))
    for b in range(B):
        got = limbs.projective_limbs_to_point(np.asarray(out)[b])
        assert got == want[b], f"row {b}"


def test_msm_windowed_identity_row():
    pts = [bn254.G1_IDENTITY] * 4
    scs = [0, 1, 2, 3]
    out = ec.msm_windowed(
        jnp.asarray(limbs.points_to_projective_limbs(pts))[None],
        jnp.asarray(limbs.scalars_to_limbs(scs))[None])
    assert bool(ec.is_identity(out)[0])


@pytest.fixture(scope="module")
def fb():
    pts = _rand_points(3)
    tables = ec.fixed_base_planes(
        jnp.asarray(limbs.points_to_projective_limbs(pts)))
    return pts, tables


def test_fixed_base_gather_matches_host(fb):
    pts, tables = fb
    B = 2
    sc_rows, want = [], []
    for _ in range(B):
        scs = [rng.randrange(bn254.R) for _ in range(3)]
        sc_rows.append(limbs.scalars_to_limbs(scs))
        want.append([bn254.g1_mul(p, s) for p, s in zip(pts, scs)])
    out = np.asarray(ec.fixed_base_gather(
        tables, jnp.asarray(np.stack(sc_rows))))
    for b in range(B):
        for t in range(3):
            got = limbs.projective_limbs_to_point(out[b, t])
            assert got == want[b][t], f"({b},{t})"


def test_fixed_base_msm_matches_host(fb):
    pts, tables = fb
    scs = [rng.randrange(bn254.R) for _ in range(3)]
    out = ec.fixed_base_msm(tables, jnp.asarray(limbs.scalars_to_limbs(scs)))
    got = limbs.projective_limbs_to_point(np.asarray(out))
    assert got == _host_msm(pts, scs)


def test_fixed_base_edge_scalars(fb):
    pts, tables = fb
    scs = [0, 1, bn254.R - 1]
    out = np.asarray(ec.fixed_base_gather(
        tables, jnp.asarray(limbs.scalars_to_limbs(scs))[None]))
    assert limbs.projective_limbs_to_point(out[0, 0]) == bn254.G1_IDENTITY
    assert limbs.projective_limbs_to_point(out[0, 1]) == pts[1]
    assert limbs.projective_limbs_to_point(out[0, 2]) == bn254.g1_neg(pts[2])


def test_to_affine_batch_matches_host():
    pts = _rand_points(5) + [bn254.G1_IDENTITY]
    # mix in non-trivial Z by summing pairs on device
    dev = jnp.asarray(limbs.points_to_projective_limbs(pts))
    doubled = ec.add(dev, dev)  # projective with Z != 1
    aff = np.asarray(ec.to_affine_batch(doubled[None]))[0]
    for k, p in enumerate(pts):
        want = bn254.g1_add(p, p)
        if want.inf:
            assert not np.any(aff[k])
        else:
            assert limbs.limbs_to_int(aff[k][0]) == want.x
            assert limbs.limbs_to_int(aff[k][1]) == want.y
