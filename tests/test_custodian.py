"""Custodian-mediated (Orion-style) ledger backend.

Mirrors reference token/services/network/orion: approval -> broadcast via
a custodian node, bounded submission retries, client-side approval
verification, and the full TokenNode lifecycle running unchanged on the
swapped backend (driver.Network boundary).
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.custodian import (
    CustodianChaincodeFacade,
    CustodianError,
    CustodianNode,
)
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus


@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    custodian_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    validator = fabtoken.new_validator(pp, Deserializer())
    cc = TokenChaincode(validator, MemoryLedger(), pp.serialize())
    bus = SessionBus()
    custodian = CustodianNode("custodian", custodian_keys, cc, bus)
    facade = CustodianChaincodeFacade(custodian, validator)
    nodes = {
        "issuer": TokenNode("issuer", issuer_keys, bus, facade,
                            auditor_name="auditor"),
        "auditor": AuditorNode("auditor", auditor_keys, bus, facade,
                               auditor_name="auditor"),
        "alice": TokenNode("alice", new_signing_identity(), bus, facade,
                           auditor_name="auditor"),
        "bob": TokenNode("bob", new_signing_identity(), bus, facade,
                         auditor_name="auditor"),
    }
    return nodes, custodian


def test_lifecycle_over_custodian(net):
    nodes, _ = net
    alice, bob = nodes["alice"], nodes["bob"]
    ev = alice.execute(alice.issue("issuer", "alice", "USD", hex(400)))
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 400

    ev = alice.execute(alice.transfer("USD", hex(150), "bob"))
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 250
    assert bob.balance("USD") == 150

    # audit trail reached the auditor through the custodian event fan-out
    recs = nodes["auditor"].auditdb.query_transactions()
    assert len(recs) == 2


def test_custodian_rejects_invalid_request(net):
    nodes, custodian = net
    with pytest.raises(CustodianError):
        custodian.request_approval("bad-tx", b"\x00garbage")
    # facade path surfaces it as an INVALID commit event
    facade = nodes["alice"].cc
    ev = facade.process_request("bad-tx", b"\x00garbage")
    assert ev.status == "INVALID" and "rejects" in ev.message


def test_broadcast_retries_transient_failures(net):
    nodes, custodian = net
    alice = nodes["alice"]
    fails = {"n": 0}

    def fail_twice(attempt):
        if fails["n"] < 2:
            fails["n"] += 1
            return True
        return False

    custodian.fault_hook = fail_twice
    ev = alice.execute(alice.issue("issuer", "alice", "USD", hex(10)))
    assert ev.status == "VALID", ev.message
    assert fails["n"] == 2  # two transient failures absorbed by retry


def test_broadcast_outage_surfaces_invalid_and_releases_locks(net):
    nodes, custodian = net
    alice = nodes["alice"]
    # fund first so the next transfer takes token locks
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(30))).status == "VALID"

    custodian.fault_hook = lambda attempt: True  # permanent outage
    ev = alice.execute(alice.transfer("USD", hex(30), "bob"))
    assert ev.status == "INVALID" and "failed after" in ev.message
    custodian.fault_hook = None
    # the selector locks were released on the INVALID event: the tokens
    # are spendable again once the custodian recovers
    ev = alice.execute(alice.transfer("USD", hex(30), "bob"))
    assert ev.status == "VALID", ev.message
    assert nodes["bob"].balance("USD") == 30


def test_double_spend_rejected_via_custodian(net):
    nodes, _ = net
    alice = nodes["alice"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(50))).status == "VALID"
    tx = alice.transfer("USD", hex(50), "bob")
    assert alice.execute(tx).status == "VALID"
    # replaying the same spent inputs must fail validation at the custodian
    ev = alice.cc.process_request("replay-" + tx.tx_id,
                                  tx.request.to_bytes())
    assert ev.status == "INVALID"
