"""Cross-process distributed tracing (obs/tracing.py + serve/rpc.py v3).

Covers the whole propagation contract:

  - :class:`SpanContext` wire codec round-trip and strict rejection of
    poisoned bytes; :func:`extract_wire_context` tolerance (counted
    drops, never an exception).
  - ``split_trace_prefix`` on raw SUBMIT_BATCH payloads (flag absent /
    flag with poisoned prefix / flag with valid prefix).
  - ``remote_parent=`` joining: a span opened with a caller's context
    inherits the caller's trace id and lands in the tracer's roots.
  - End-to-end over loopback TCP (crypto-free :class:`StubZK`): the
    client's ``rpc.call``, the server's ``rpc.serve`` and the service's
    ``serve.request`` spans share ONE trace id, and the
    ``rpc_call_seconds`` exemplar resolves to it.
  - Poisoned/missing context adversity: truncated bytes, zero ids, a
    v2 peer sending no context — every frame is SERVED, the drop is a
    counted ``trace_drops_total{reason}`` increment, and there is never
    a frame error.
  - :class:`SpanSpoolExporter` bounded buffer + drop accounting +
    torn-spool tolerance, and ``assemble_traces`` fleet grouping.
  - The two-process acceptance path: client -> supervised TCP sidecar
    with a shared obs spool; the federated ``/tracez`` serves one
    assembled trace spanning both processes.
"""

import struct
import time

import pytest

from fabric_token_sdk_tpu.obs import GLOBAL, TRACER
from fabric_token_sdk_tpu.obs.tracing import (CONTEXT_WIRE_SIZE,
                                              SpanContext,
                                              SpanSpoolExporter, Tracer,
                                              assemble_traces,
                                              extract_wire_context,
                                              read_span_spool)
from fabric_token_sdk_tpu.serve.rpc import (FLAG_TRACE_CONTEXT, RESULT,
                                            SUBMIT, SUBMIT_BATCH,
                                            split_trace_prefix)

from test_rpc import (_Harness, _await_count, _batch_payload, _client,
                      _count, _handshake)


@pytest.fixture(autouse=True)
def _clean_registries():
    GLOBAL.reset()
    TRACER.clear()
    yield
    TRACER.clear()


# ------------------------------------------------------------ wire codec
def test_span_context_roundtrip():
    ctx = SpanContext(trace_id=0xDEADBEEFCAFE, span_id=42, sampled=True)
    data = ctx.to_bytes()
    assert len(data) == CONTEXT_WIRE_SIZE == 17
    back = SpanContext.from_bytes(data)
    assert back == ctx
    # the sampled bit survives both ways
    off = SpanContext(trace_id=7, span_id=9, sampled=False)
    assert SpanContext.from_bytes(off.to_bytes()).sampled is False


@pytest.mark.parametrize("poison", [
    b"",                                     # empty
    b"abc",                                  # truncated
    b"\x00" * 17,                            # zero trace AND span id
    struct.pack(">QQB", 0, 5, 1),            # zero trace id
    struct.pack(">QQB", 5, 0, 1),            # zero span id
    b"\xff" * 18,                            # too long
    "not-bytes",                             # wrong type entirely
])
def test_strict_decode_rejects_poison(poison):
    with pytest.raises(ValueError):
        SpanContext.from_bytes(poison)


def test_extract_counts_drops_and_never_raises():
    assert extract_wire_context(None, GLOBAL) is None
    assert _count("trace_drops_total", reason="missing") == 1
    assert extract_wire_context(b"short", GLOBAL) is None
    assert extract_wire_context(b"\x00" * 17, GLOBAL) is None
    assert _count("trace_drops_total", reason="invalid_context") == 2
    # a valid context still decodes through the tolerant path
    ctx = extract_wire_context(SpanContext(3, 4).to_bytes(), GLOBAL)
    assert ctx == SpanContext(3, 4, sampled=True)


def test_split_trace_prefix():
    payload = b"columnar-bytes-here"
    # no flag: pass-through, and NOT counted as a drop (v1/v2 frame)
    ctx, rest = split_trace_prefix(payload, 0, GLOBAL)
    assert ctx is None and rest == payload
    assert _count("trace_drops_total") == 0
    # flag + valid prefix: context off, payload intact
    wire = SpanContext(11, 22).to_bytes() + payload
    ctx, rest = split_trace_prefix(wire, FLAG_TRACE_CONTEXT, GLOBAL)
    assert ctx == SpanContext(11, 22) and rest == payload
    # flag + short payload: counted, payload untouched
    ctx, rest = split_trace_prefix(b"tiny", FLAG_TRACE_CONTEXT, GLOBAL)
    assert ctx is None and rest == b"tiny"
    assert _count("trace_drops_total", reason="invalid_context") == 1


# -------------------------------------------------------- remote parent
def test_remote_parent_joins_callers_trace():
    tracer = Tracer(provider=GLOBAL)
    with tracer.span("rpc.call") as caller:
        ctx = caller.context()
    with tracer.span("rpc.serve", remote_parent=ctx) as served:
        assert served.trace_id == ctx.trace_id
        assert served.parent_id == ctx.span_id
        assert served.attributes.get("remote_parent") is True
    # the remote child is a LOCAL root: its parent object lives in
    # another process, so /tracez must still export it
    assert any(sp.name == "rpc.serve" for sp in tracer.root_snapshot())
    # a LOCAL parent always wins over remote_parent
    with tracer.span("outer") as outer:
        with tracer.span("inner", remote_parent=ctx) as inner:
            assert inner.trace_id == outer.trace_id != ctx.trace_id


def test_unsampled_context_propagates_sampled_bit():
    tracer = Tracer(provider=GLOBAL)
    ctx = SpanContext(trace_id=5, span_id=6, sampled=False)
    with tracer.span("rpc.serve", remote_parent=ctx) as sp:
        assert sp.sampled is False


def test_ids_are_epoch_offset_for_cross_process_uniqueness():
    from fabric_token_sdk_tpu.obs import tracing as t
    ids = {t._next_id() for _ in range(100)}
    assert len(ids) == 100
    assert all(i > t._ID_EPOCH and i < 2 ** 64 for i in ids)


# ------------------------------------------- end-to-end over loopback TCP
def _spans_named(name, minimum=1, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        found = [sp for sp in TRACER.finished if sp.name == name]
        if len(found) >= minimum:
            return found
        time.sleep(0.01)
    raise AssertionError(f"no {minimum} finished {name!r} span(s); have "
                         f"{[sp.name for sp in TRACER.finished]}")


def test_rpc_call_serve_request_share_one_trace():
    """The tentpole invariant, in-process: one submit produces client
    ``rpc.call``, server ``rpc.serve`` and service ``serve.request``
    spans under a single trace id, with the ``rpc_call_seconds``
    exemplar resolving to it."""
    with _Harness() as h:
        cli = _client(h.address, tms_id="traced")
        try:
            out = cli.submit_range([True, False], [None, None])
            assert out.tolist() == [True, False]
            assert cli.server_trace is True
        finally:
            cli.close()
        (call,) = _spans_named("rpc.call")
        (serve,) = _spans_named("rpc.serve")
        requests = _spans_named("serve.request", minimum=2)
        assert serve.trace_id == call.trace_id
        assert serve.parent_id == call.span_id
        for req_span in requests:
            assert req_span.trace_id == call.trace_id
            assert req_span.parent_id == serve.span_id
        # exemplar: the latency histogram resolves to the fleet trace
        exemplars = [e for e in GLOBAL.exemplars()
                     if e["family"] == "rpc_call_seconds"]
        assert exemplars, GLOBAL.exemplars()
        assert exemplars[0]["exemplar"]["trace_id"] \
            == f"{call.trace_id:016x}"
        assert _count("span_exemplars_total",
                      family="rpc_call_seconds") >= 1
        # server-side wait histogram carries the same trace's exemplar
        waits = [e for e in GLOBAL.exemplars()
                 if e["family"] == "serve_wait_seconds"]
        assert waits and waits[0]["exemplar"]["trace_id"] \
            == f"{call.trace_id:016x}"
        assert _count("rpc_frame_errors_total") == 0


def test_batch_frame_joins_trace_via_flagged_prefix():
    with _Harness() as h:
        cli = _client(h.address, tms_id="bt")
        try:
            out = cli.submit_range_batch([True, False, True], [None] * 3)
            assert out.tolist() == [True, False, True]
        finally:
            cli.close()
        (call,) = _spans_named("rpc.call")
        (serve_b,) = _spans_named("rpc.serve_batch")
        assert serve_b.trace_id == call.trace_id
        assert serve_b.parent_id == call.span_id
        assert _count("rpc_frame_errors_total") == 0


# ------------------------------------------------- poisoned context frames
def _submit_and_get_result(sock, body):
    from fabric_token_sdk_tpu.serve.rpc import (recv_frame_sock,
                                                send_frame_sock)
    send_frame_sock(sock, SUBMIT, body)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        try:
            frame = recv_frame_sock(sock, body_timeout_s=5.0)
        except TimeoutError:
            continue
        assert frame is not None
        if frame[0] == RESULT:
            return frame[1]
    raise AssertionError("no RESULT frame")


@pytest.mark.parametrize("reason,tc", [
    ("invalid_context", b"abc"),           # truncated context bytes
    ("invalid_context", b"\x00" * 17),     # zero trace id
    ("missing", None),                     # v2 peer: no context at all
])
def test_poisoned_or_missing_context_is_served_and_counted(reason, tc):
    """THE adversity contract: bad trace context never fails a frame —
    the rows verify, the drop is counted, the connection lives."""
    with _Harness() as h:
        sock = _handshake(h.address, tms="poison")
        try:
            body = {"req_id": 1, "kind": "range", "lane": "bulk",
                    "rows": 2, "deadline": time.time() + 30.0,
                    "payload": ([True, False], [None, None])}
            if tc is not None:
                body["tc"] = tc
            reply = _submit_and_get_result(sock, body)
        finally:
            sock.close()
        assert reply["status"] == "ok"
        assert reply["verdicts"] == [True, False]
        assert "tc" not in reply  # nothing valid to echo
        _await_count("trace_drops_total", reason=reason)
        assert _count("rpc_frame_errors_total") == 0


def test_poisoned_batch_prefix_is_served_and_counted():
    """SUBMIT_BATCH with FLAG_TRACE_CONTEXT but an all-zero (invalid)
    17-byte prefix: the prefix is stripped + counted, the batch decodes
    and serves normally."""
    from fabric_token_sdk_tpu.serve.rpc import (recv_frame_sock,
                                                send_raw_frame_sock)
    with _Harness() as h:
        sock = _handshake(h.address, tms="bpoison")
        try:
            payload = b"\x00" * CONTEXT_WIRE_SIZE + _batch_payload()
            send_raw_frame_sock(sock, SUBMIT_BATCH, payload,
                                flags=FLAG_TRACE_CONTEXT)
            deadline = time.monotonic() + 10.0
            reply = None
            while time.monotonic() < deadline:
                try:
                    frame = recv_frame_sock(sock, body_timeout_s=5.0)
                except TimeoutError:
                    continue
                assert frame is not None
                if frame[0] == RESULT:
                    reply = frame[1]
                    break
        finally:
            sock.close()
        assert reply is not None and reply["status"] == "ok"
        assert reply["verdicts"] == [True, False]
        _await_count("trace_drops_total", reason="invalid_context")
        assert _count("rpc_frame_errors_total") == 0


# ------------------------------------------------------- spool exporter
def test_exporter_bounded_buffer_counts_drops(tmp_path):
    tracer = Tracer(provider=GLOBAL)
    exp = SpanSpoolExporter(tmp_path, node="n0", tracer=tracer,
                            provider=GLOBAL, keep_spans=4)
    exp.attach()
    try:
        for i in range(10):
            with tracer.span("storm", i=i):
                pass
    finally:
        exp.detach()
    # ring kept the newest 4; the 6 evictions are counted
    assert _count("trace_drops_total", reason="buffer") == 6
    assert _count("trace_spans_total", node="n0") == 10
    assert exp.publish() == 4
    records = read_span_spool(tmp_path)
    assert len(records) == 4
    assert {r["node"] for r in records} == {"n0"}
    assert [r["attributes"]["i"] for r in records] == [6, 7, 8, 9]


def test_exporter_drops_unsampled_spans(tmp_path):
    tracer = Tracer(provider=GLOBAL)
    exp = SpanSpoolExporter(tmp_path, node="n1", tracer=tracer,
                            provider=GLOBAL)
    exp.attach()
    try:
        ctx = SpanContext(trace_id=9, span_id=8, sampled=False)
        with tracer.span("quiet", remote_parent=ctx):
            pass
    finally:
        exp.detach()
    assert _count("trace_drops_total", reason="unsampled") == 1
    assert exp.publish() == 0


def test_spool_reader_skips_torn_lines(tmp_path):
    good = ('{"node": "n", "name": "s", "trace_id": "ab", '
            '"span_id": "cd", "parent_id": null, "duration": 0.1, '
            '"wall_end": 1.0, "attributes": {}}')
    (tmp_path / "n.spans.jsonl").write_text(
        good + "\n{torn mid-write\n\nnot json at all\n")
    (tmp_path / "other.spans.jsonl").write_text("")
    records = read_span_spool(tmp_path)
    assert len(records) == 1 and records[0]["trace_id"] == "ab"
    # a missing directory is an empty fleet, not an error
    assert read_span_spool(tmp_path / "nope") == []


def test_assemble_traces_orders_parent_first():
    records = [
        {"trace_id": "t1", "span_id": "c", "parent_id": "b",
         "name": "serve.request", "wall_end": 3.0},
        {"trace_id": "t1", "span_id": "a", "parent_id": None,
         "name": "rpc.call", "wall_end": 5.0},
        {"trace_id": "t1", "span_id": "b", "parent_id": "a",
         "name": "rpc.serve", "wall_end": 4.0},
        {"trace_id": "t2", "span_id": "z", "parent_id": None,
         "name": "other", "wall_end": 1.0},
    ]
    traces = assemble_traces(records)
    assert set(traces) == {"t1", "t2"}
    assert [r["name"] for r in traces["t1"]] \
        == ["rpc.call", "rpc.serve", "serve.request"]


# --------------------------------------------- two-process acceptance
@pytest.mark.slow
def test_two_process_sidecar_produces_one_federated_trace(tmp_path):
    """The PR's acceptance path: client -> supervised TCP sidecar
    (crypto-free StubZK), both publishing spans into one obs spool;
    the federated /tracez shows ONE trace containing the client's
    ``rpc.call`` and the sidecar's ``rpc.serve`` + ``serve.request``
    spans, and the client-side exemplar resolves to that trace."""
    from fabric_token_sdk_tpu.obs.aggregate import FleetAggregator
    from fabric_token_sdk_tpu.obs.telemetry import TelemetryServer
    from fabric_token_sdk_tpu.serve.sidecar import RpcSidecar
    from fabric_token_sdk_tpu.serve.worker import stub_zk_factory

    spool = tmp_path / "spool"
    exporter = SpanSpoolExporter(spool, node="client0", tracer=TRACER,
                                 provider=GLOBAL)
    exporter.attach()
    sidecar = RpcSidecar(stub_zk_factory, prewarm=False,
                         obs_spool_dir=spool, node="sidecar0")
    sidecar.spawn()
    cli = _client(sidecar.address, tms_id="e2e")
    try:
        cli.wait_ready(timeout_s=180.0)
        out = cli.submit_range([True, False], [None, None])
        assert out.tolist() == [True, False]
    finally:
        cli.close()
        exporter.detach()
    exporter.publish()
    sidecar.stop()  # SIGTERM -> drain -> final span publish

    (call,) = [sp for sp in TRACER.finished if sp.name == "rpc.call"]
    trace_hex = f"{call.trace_id:016x}"
    records = read_span_spool(spool)
    assert {r["node"] for r in records} >= {"client0", "sidecar0"}
    traces = assemble_traces(records)
    assert trace_hex in traces, sorted(traces)
    names = {r["name"] for r in traces[trace_hex]}
    assert {"rpc.call", "rpc.serve", "serve.request"} <= names
    nodes = {r["node"] for r in traces[trace_hex]}
    assert nodes == {"client0", "sidecar0"}

    # federated /tracez serves the same assembly
    telemetry = TelemetryServer()
    telemetry.attach_federator(FleetAggregator(spool))
    code, ctype, body = telemetry.render("/tracez")
    assert code == 200 and ctype == "application/json"
    import json as _json
    doc = _json.loads(body)
    assert "traceEvents" in doc  # chrome-trace view is still there
    assert doc["node"] == TRACER.node
    assert trace_hex in doc["traces"]
    assert {r["name"] for r in doc["traces"][trace_hex]} >= {
        "rpc.call", "rpc.serve", "serve.request"}

    # the latency exemplar resolves to the SAME fleet trace
    exemplars = [e for e in GLOBAL.exemplars()
                 if e["family"] == "rpc_call_seconds"]
    assert exemplars and exemplars[0]["exemplar"]["trace_id"] == trace_hex
