"""Field kernel vs pure-Python oracle (fabric_token_sdk_tpu.crypto.bn254)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import field, limbs

rng = random.Random(0xF1E1D)


def _rand_vals(n, mod):
    edge = [0, 1, 2, mod - 1, mod - 2, (mod - 1) // 2]
    vals = edge + [rng.randrange(mod) for _ in range(n - len(edge))]
    return vals[:n]


@pytest.mark.parametrize("spec,mod", [(field.FP, bn254.P), (field.FR, bn254.R)])
def test_mont_mul_roundtrip_and_product(spec, mod):
    n = 32
    a_int = _rand_vals(n, mod)
    b_int = _rand_vals(n, mod)[::-1]
    mont_r = limbs.MONT_R
    a = jnp.asarray(limbs.ints_to_limbs([x * mont_r % mod for x in a_int]))
    b = jnp.asarray(limbs.ints_to_limbs([x * mont_r % mod for x in b_int]))
    out = np.asarray(jax.jit(field.mont_mul, static_argnums=2)(a, b, spec))
    for i in range(n):
        got = limbs.limbs_to_int(out[i]) * pow(mont_r, -1, mod) % mod
        assert got == a_int[i] * b_int[i] % mod, f"mismatch at {i}"


@pytest.mark.parametrize("spec,mod", [(field.FP, bn254.P), (field.FR, bn254.R)])
def test_add_sub_neg(spec, mod):
    n = 32
    a_int = _rand_vals(n, mod)
    b_int = _rand_vals(n, mod)[::-1]
    a = jnp.asarray(limbs.ints_to_limbs(a_int))
    b = jnp.asarray(limbs.ints_to_limbs(b_int))
    s = np.asarray(jax.jit(field.add, static_argnums=2)(a, b, spec))
    d = np.asarray(jax.jit(field.sub, static_argnums=2)(a, b, spec))
    ng = np.asarray(jax.jit(field.neg, static_argnums=1)(a, spec))
    for i in range(n):
        assert limbs.limbs_to_int(s[i]) == (a_int[i] + b_int[i]) % mod
        assert limbs.limbs_to_int(d[i]) == (a_int[i] - b_int[i]) % mod
        assert limbs.limbs_to_int(ng[i]) == (-a_int[i]) % mod


def test_to_from_mont():
    n = 16
    vals = _rand_vals(n, bn254.P)
    a = jnp.asarray(limbs.ints_to_limbs(vals))
    roundtrip = jax.jit(lambda x: field.from_mont(field.to_mont(x, field.FP), field.FP))
    back = np.asarray(roundtrip(a))
    for i in range(n):
        assert limbs.limbs_to_int(back[i]) == vals[i]


def test_is_zero_and_select():
    a = jnp.asarray(limbs.ints_to_limbs([0, 1, bn254.P - 1, 0]))
    z = np.asarray(field.is_zero(a))
    assert list(z) == [True, False, False, True]
