"""Tier-1 guard: the stable metric families must exist in the source.

ROADMAP.md declares the metric family names a stable interface —
dashboards, the bench harness, and the obs report all key on them, so a
rename or deletion is a breaking change. The smoke tests
(test_obs_smoke.py, test_serve_smoke.py) verify families light up under
load, but only for the layers they exercise; this guard covers the whole
inventory cheaply by scanning the package source for each registered
family name. A family that disappears (renamed, dropped in a refactor)
fails here with the missing name, before any dashboard goes dark.
"""

from pathlib import Path

import pytest

_PKG = Path(__file__).resolve().parent.parent

#: Every stable family, by subsystem (keep sorted within each block).
STABLE_FAMILIES = (
    # models/ pipeline + device verifiers
    "adjust_points_total",
    "pipeline_batch_seconds",
    "pipeline_batches_total",
    "pipeline_pad_rows_total",
    "pipeline_pad_waste_ratio",
    "pipeline_phase_seconds",
    "pipeline_rows_total",
    "pipeline_steady_seconds",
    "sigma_dispatches_total",
    "sigma_pad_rows_total",
    "sigma_rows_total",
    "zk_block_actions_total",
    "zk_blocks_verified_total",
    "zk_device_oracle_disagreements_total",
    "zk_range_batch_verify_seconds",
    "zk_range_proofs_verified_total",
    "zk_sigma_verify_seconds",
    # services/ tiers
    "selector_insufficient_funds_total",
    "selector_retries_total",
    "selector_select_seconds",
    "selector_tokens_locked_total",
    "tcc_commit_seconds",
    "tcc_process_request_seconds",
    "tcc_request_status_total",
    "tcc_requests_total",
    "tcc_translate_seconds",
    "tcc_validate_seconds",
    "ttx_collect_endorsements_seconds",
    "ttx_commit_ingest_seconds",
    "ttx_commits_total",
    "ttx_execute_seconds",
    "ttx_executions_total",
    "ttx_ordering_finality_seconds",
    "txgen_op_seconds",
    "txgen_ops_total",
    # serve/ frontend
    "serve_batch_fill_ratio",
    "serve_batch_rows",
    "serve_batches_total",
    "serve_deadline_miss_total",
    "serve_dispatch_seconds",
    "serve_prewarm_seconds",
    "serve_queue_depth",
    "serve_requests_total",
    "serve_results_total",
    "serve_shed_total",
    "serve_tenant_drains_total",
    "serve_wait_seconds",
    # serve/ per-tenant SLO plane (tenant-labelled latency + shedding)
    "serve_tenant_e2e_seconds",
    "serve_tenant_queue_seconds",
    "serve_tenant_sheds_total",
    # serve/ per-device dispatch lanes (multi-chip continuous batching)
    "lane_busy_seconds",
    "lane_dispatch_total",
    "lane_inflight",
    "lane_rows_total",
    # models/ multi-chip mesh pipeline
    "mesh_allgather_bytes_total",
    "mesh_chunk_dispatches_total",
    "mesh_devices",
    "mesh_pad_rows_total",
    # serve/ network front door (RPC sidecar)
    "rpc_accept_shed_total",
    "rpc_batch_bytes_total",
    "rpc_batch_frames_total",
    "rpc_batch_rows_total",
    "rpc_call_seconds",
    "rpc_connections_active",
    "rpc_connections_total",
    "rpc_conns",
    "rpc_credit_waits_total",
    "rpc_credits",
    "rpc_deadline_expired_total",
    "rpc_decode_seconds",
    "rpc_frame_errors_total",
    "rpc_frames_total",
    "rpc_goaways_total",
    "rpc_hedges_total",
    "rpc_loops",
    "rpc_redials_total",
    "rpc_requests_total",
    "rpc_result_batch_bytes_total",
    "rpc_result_batch_frames_total",
    "rpc_result_batch_rows_total",
    "rpc_tenant_deficit",
    "rpc_wakeups_total",
    # serve/ pipe worker single-flight contention
    "serve_worker_lock_wait_seconds",
    # serve/ write-ahead log
    "wal_appends_total",
    "wal_bytes_written_total",
    "wal_compactions_total",
    "wal_open_requests",
    "wal_recovery_seconds",
    "wal_replayed_total",
    "wal_segments_total",
    "wal_torn_records_total",
    # resilience/ supervisor + bench kill schedule
    "crash_child_up",
    "crash_escalations_total",
    "crash_failures_total",
    "crash_injected_signals_total",
    "crash_restarts_total",
    "crash_rto_seconds",
    # resilience/
    "resil_breaker_state",
    "resil_breaker_transitions_total",
    "resil_fallback_batches_total",
    "resil_fallback_rows_total",
    "resil_injected_faults_total",
    "resil_retries_total",
    "resil_watchdog_trips_total",
    # obs/ live telemetry plane
    "telemetry_scrape_seconds",
    "telemetry_scrapes_total",
    # obs/ SLO burn-rate monitor
    "slo_availability_ratio",
    "slo_error_budget_burn_rate",
    "slo_fast_burn_active",
    "slo_fast_burn_trips_total",
    "slo_p99_seconds",
    "slo_window_requests",
    # obs/ per-tenant SLO monitor + fleet fairness
    "slo_fairness_index",
    "slo_tenant_availability",
    "slo_tenant_budget_remaining",
    "slo_tenant_burn_rate",
    "slo_tenant_evictions_total",
    "slo_tenant_p99_seconds",
    # obs/ device profiling
    "profile_bucket_bytes",
    "profile_bucket_flops",
    "profile_compile_cache_total",
    "profile_compile_seconds",
    "profile_device_bytes_in_use",
    "profile_device_peak_bytes",
    # obs/ flight recorder
    "journal_dropped_total",
    "journal_events_total",
    "journal_incidents_total",
    # obs/ heartbeat + stall detection
    "hb_beats_total",
    "hb_last_age_seconds",
    "hb_stalls_total",
    # obs/ fleet federation
    "fleet_merge_conflicts_total",
    "fleet_node_age_seconds",
    "fleet_nodes",
    "fleet_samples",
    "fleet_tenants",
    # obs/ distributed tracing (cross-process trace plane)
    "span_exemplars_total",
    "trace_drops_total",
    "trace_spans_total",
    # prover/ device proof synthesis + harness corpus
    "prover_chunks_total",
    "prover_corpus_proofs_total",
    "prover_pad_rows_total",
    "prover_proofs_total",
    "prover_rows_total",
    "prover_synthesize_seconds",
)

#: Families whose names are built dynamically: family -> the source
#: fragment that constructs it (services/db/sqldb.py templates the method
#: name into ``db_<method>_seconds``).
DYNAMIC_FAMILIES = {
    "db_store_token_seconds": 'db_{fn.__name__}_seconds',
}


def _source_corpus() -> str:
    chunks = [(_PKG / "bench.py").read_text()]
    for path in sorted((_PKG / "fabric_token_sdk_tpu").rglob("*.py")):
        chunks.append(path.read_text())
    return "\n".join(chunks)


def test_stable_metric_families_present_in_source():
    corpus = _source_corpus()
    missing = [fam for fam in STABLE_FAMILIES if fam not in corpus]
    assert not missing, (
        "stable metric families missing from the source (renaming or "
        f"dropping one is a breaking interface change): {missing}")


def test_dynamic_metric_families_still_constructed():
    corpus = _source_corpus()
    for fam, fragment in DYNAMIC_FAMILIES.items():
        assert fragment in corpus, (
            f"dynamic family {fam} lost its constructor "
            f"(expected source fragment {fragment!r})")


def test_no_duplicate_family_entries():
    assert len(set(STABLE_FAMILIES)) == len(STABLE_FAMILIES)


def test_tenant_labelled_registrations_carry_bounded_tag():
    """Every instrument registration labelled by ``tms_id`` is an
    unbounded-cardinality hazard: one series per client id, forever,
    unless something evicts it. The convention is a ``# tenant-bounded:``
    comment at the registration site naming the eviction path (LRU
    bound + remove_series). This guard fails on any new ``tms_id=``
    registration without the tag — add the eviction wiring AND the
    comment, not just the metric."""
    import ast

    instruments = {"counter", "gauge", "histogram"}
    offenders = []
    files = [_PKG / "bench.py"]
    files += sorted((_PKG / "fabric_token_sdk_tpu").rglob("*.py"))
    for path in files:
        src = path.read_text()
        lines = src.splitlines()
        for node in ast.walk(ast.parse(src)):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in instruments):
                continue
            if not any(kw.arg == "tms_id" for kw in node.keywords):
                continue
            # the tag must sit on (or within ten lines above) the call
            window = "\n".join(lines[max(0, node.lineno - 11):node.lineno])
            if "# tenant-bounded:" not in window:
                offenders.append(
                    f"{path.relative_to(_PKG)}:{node.lineno}")
    assert not offenders, (
        "tms_id-labelled metric registrations without a '# tenant-"
        f"bounded:' eviction note: {offenders}")


@pytest.mark.parametrize("prefix", ["ttx_", "tcc_", "zk_", "sigma_",
                                    "pipeline_", "selector_", "serve_",
                                    "txgen_", "resil_", "telemetry_",
                                    "slo_", "profile_", "journal_",
                                    "hb_", "fleet_", "wal_", "crash_",
                                    "rpc_", "mesh_", "lane_", "prover_",
                                    "trace_", "span_"])
def test_every_stable_prefix_is_covered(prefix):
    # the inventory above must not silently drop a whole subsystem
    assert any(f.startswith(prefix) for f in STABLE_FAMILIES), prefix
