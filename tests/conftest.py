"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real-TPU execution is exercised by bench.py and the driver's graft entry;
the test suite validates numerics and sharding on the CPU backend so it runs
anywhere (SURVEY.md §7: multi-chip is tested via virtual devices).

The environment ships an `axon` TPU plugin that imports jax from
sitecustomize at interpreter start with JAX_PLATFORMS=axon — env vars set
here are too late, so the platform override must go through jax.config.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
# stack limits must rise BEFORE jax exists: worker-thread stacks via
# setrlimit, the MAIN thread via re-exec (serialize/deserialize of the big
# cached executables runs natively on the main thread)
from fabric_token_sdk_tpu.utils.jaxcfg import (ensure_main_thread_stack,
                                               raise_stack_limit)  # noqa: E402

ensure_main_thread_stack()
raise_stack_limit()

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in flags:
    # Single-core host: parallel LLVM codegen buys nothing and its extra
    # compiler threads/memory are implicated in nondeterministic SIGSEGVs
    # while compiling the big MSM kernels (faulthandler dumps inside
    # _compile_and_write_cache). One split = one stable compile.
    flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402  (after XLA_FLAGS so the CPU client sees it)

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the limbed EC kernels trace to large graphs
# (256-step fori_loop bodies); caching makes re-runs cheap. Set via config,
# not env — jax is already imported (sitecustomize), so env vars are too late.
from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache  # noqa: E402

configure_jax_cache()


# ---------------------------------------------------------------------------
# Heavy-kernel module isolation
#
# Full-suite runs (pytest tests/ -q) accumulate hundreds of live XLA:CPU
# executables; with that state, DESERIALIZING the biggest cached kernels
# (the combined RLC MSM) segfaults inside jaxlib's compilation-cache read
# (jax/_src/compilation_cache.py get_executable_and_time — reproduced at
# the same site across rounds; the identical read succeeds in a fresh
# process every time). Modules that compile those kernels therefore run in
# their OWN pytest subprocess during multi-module sessions: each gets the
# empirically-green solo configuration, the parent session never loads the
# big executables, and per-test results are re-reported transparently.
# ---------------------------------------------------------------------------

_HEAVY_MODULES = {
    "test_range_verifier.py",
    "test_range_verifier_multibit.py",
    "test_range_verifier_sharded.py",
    "test_prover_parity.py",
    "test_zkatdlog_e2e.py",
    "test_zk_audit.py",
    "test_ops_windowed.py",
    "test_parallel.py",
    "test_sigma_device.py",
    "test_serve_smoke.py",
}
#: Modules whose parametrized variants each load their OWN big kernel set
#: (multibit: 16/32/64-bit tables+executables) — one process per TEST,
#: or the in-process accumulation crosses the crash threshold again.
_HEAVY_PER_TEST = {"test_range_verifier_multibit.py"}
_ISOLATE_ENV = "FTS_ISOLATED_SUBPROCESS"
_SUBPROC_RESULTS: dict = {}
_GROUP_NODEIDS: dict = {}


def _session_module_names(session):
    return {Path(str(item.fspath)).name for item in session.items}


def _group_key(item):
    name = Path(str(item.fspath)).name
    if name in _HEAVY_PER_TEST:
        return (name, item.nodeid)
    return (name, "")


def pytest_collection_modifyitems(session, config, items):
    if os.environ.get(_ISOLATE_ENV):
        return  # inside an isolation subprocess: run normally
    if len(_session_module_names(session)) < 2:
        return  # single-module invocation: solo config already; no need
    for item in items:
        if Path(str(item.fspath)).name in _HEAVY_MODULES:
            item._fts_isolate = True
            _GROUP_NODEIDS.setdefault(_group_key(item), []).append(
                item.nodeid)


def _run_group_subprocess(nodeids: list) -> dict:
    """Run one isolation group in a fresh pytest process; id -> outcome."""
    import subprocess
    import tempfile
    import xml.etree.ElementTree as ET

    with tempfile.NamedTemporaryFile(suffix=".xml", delete=False) as fh:
        xml_path = fh.name
    env = dict(os.environ)
    env[_ISOLATE_ENV] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", *nodeids, "-q", "--tb=line",
             f"--junitxml={xml_path}"],
            cwd=str(Path(__file__).resolve().parent.parent),
            env=env, capture_output=True, text=True, timeout=5400)
    except subprocess.TimeoutExpired as exc:
        try:
            os.unlink(xml_path)
        except OSError:
            pass
        return {"__error__": (
            "failed", f"isolated subprocess timed out: {exc}")}
    results: dict = {}
    try:
        root = ET.parse(xml_path).getroot()
        for case in root.iter("testcase"):
            cls = case.attrib.get("classname", "")
            name = case.attrib.get("name", "")
            # junit classname tests.test_mod.TestCls -> nodeid pieces
            parts = cls.split(".")
            mod_idx = next((i for i, p in enumerate(parts)
                            if p.startswith("test_")), len(parts) - 1)
            nodeparts = parts[mod_idx + 1:] + [name]
            key = "::".join(nodeparts)
            if case.find("failure") is not None \
                    or case.find("error") is not None:
                node = case.find("failure")
                if node is None:
                    node = case.find("error")
                results[key] = ("failed",
                                (node.attrib.get("message", "") or "")
                                + "\n" + (node.text or ""))
            elif case.find("skipped") is not None:
                node = case.find("skipped")
                results[key] = ("skipped",
                                node.attrib.get("message", "") or "skipped")
            else:
                results[key] = ("passed", "")
    except Exception as exc:  # subprocess crashed before writing results
        results["__error__"] = (
            "failed",
            f"isolated subprocess failed (rc={proc.returncode}): {exc}\n"
            + proc.stdout[-2000:] + proc.stderr[-2000:])
    finally:
        try:
            os.unlink(xml_path)
        except OSError:
            pass
    if proc.returncode not in (0, 1) and "__error__" not in results:
        results["__crash__"] = (
            "failed",
            f"isolated subprocess died rc={proc.returncode}\n"
            + proc.stdout[-2000:] + proc.stderr[-2000:])
    return results


def pytest_runtest_protocol(item, nextitem):
    if not getattr(item, "_fts_isolate", False):
        return None
    from _pytest.reports import TestReport

    path = Path(str(item.fspath))
    key = _group_key(item)
    if key not in _SUBPROC_RESULTS:
        _SUBPROC_RESULTS[key] = _run_group_subprocess(
            _GROUP_NODEIDS.get(key, [item.nodeid]))
    results = _SUBPROC_RESULTS[key]

    # nodeid within the module: "TestCls::test_name" or "test_name[param]"
    local = item.nodeid.split("::", 1)[1] if "::" in item.nodeid else \
        item.nodeid
    outcome, detail = results.get(
        local, results.get("__error__",
                           results.get("__crash__",
                                       ("failed",
                                        "no result from subprocess"))))

    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    if outcome == "passed":
        rep = TestReport(item.nodeid, item.location, {}, "passed", None,
                         "call", [], 0.0)
    elif outcome == "skipped":
        rep = TestReport(item.nodeid, item.location, {}, "skipped",
                         (str(path), 0, detail), "call", [], 0.0)
    else:
        rep = TestReport(item.nodeid, item.location, {}, "failed",
                         f"[isolated subprocess] {detail}", "call", [], 0.0)
    item.ihook.pytest_runtest_logreport(report=rep)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True
