"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real-TPU execution is exercised by bench.py and the driver's graft entry;
the test suite validates numerics and sharding on the CPU backend so it runs
anywhere (SURVEY.md §7: multi-chip is tested via virtual devices).

The environment ships an `axon` TPU plugin that imports jax from
sitecustomize at interpreter start with JAX_PLATFORMS=axon — env vars set
here are too late, so the platform override must go through jax.config.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
# stack limit must rise BEFORE jax spawns compilation threads
from fabric_token_sdk_tpu.utils.jaxcfg import raise_stack_limit  # noqa: E402

raise_stack_limit()

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in flags:
    # Single-core host: parallel LLVM codegen buys nothing and its extra
    # compiler threads/memory are implicated in nondeterministic SIGSEGVs
    # while compiling the big MSM kernels (faulthandler dumps inside
    # _compile_and_write_cache). One split = one stable compile.
    flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402  (after XLA_FLAGS so the CPU client sees it)

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the limbed EC kernels trace to large graphs
# (256-step fori_loop bodies); caching makes re-runs cheap. Set via config,
# not env — jax is already imported (sitecustomize), so env vars are too late.
from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache  # noqa: E402

configure_jax_cache()
