"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real-TPU execution is exercised by bench.py and the driver's graft entry;
the test suite validates numerics and sharding on the CPU backend so it runs
anywhere (SURVEY.md §7: multi-chip is tested via virtual devices).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Persistent compilation cache: the limbed EC kernels trace to large graphs
# (256-step fori_loop bodies of ~20k HLO ops); caching makes re-runs cheap.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
