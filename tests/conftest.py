"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real-TPU execution is exercised by bench.py and the driver's graft entry;
the test suite validates numerics and sharding on the CPU backend so it runs
anywhere (SURVEY.md §7: multi-chip is tested via virtual devices).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
