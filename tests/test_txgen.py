"""txgen load generator: deterministic mix, metrics, concurrent stress.

Mirrors reference integration/nwo/txgen (distribution model + executors +
metrics) and the dlogstress suite shape (stress over the fungible flow).
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.harness.txgen import LoadGenerator, TxProfile
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus


@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    bus = SessionBus()
    TokenNode("issuer", issuer_keys, bus, cc, auditor_name="auditor")
    AuditorNode("auditor", auditor_keys, bus, cc, auditor_name="auditor")
    users = [TokenNode(n, new_signing_identity(), bus, cc,
                       auditor_name="auditor")
             for n in ("alice", "bob", "charlie")]
    return users


def test_load_run_with_metrics(net):
    gen = LoadGenerator(net, "issuer", seed=11)
    report = gen.run(40, bootstrap_value=500)
    s = report.summary()
    assert s["total"] == 40 + len(net)
    # bootstrapped wallets: the weighted mix should mostly succeed
    assert s["succeeded"] >= s["total"] * 0.8, report.failures_by_error()
    assert s["tx_per_sec"] > 0
    assert s["p95_latency_s"] >= s["p50_latency_s"] >= 0
    # conservation: total balance == issued - redeemed
    issued = sum(o.seconds >= 0 and o.ok and o.op == "issue"
                 for o in report.outcomes)  # count only
    assert issued > 0


def test_deterministic_mix(net):
    # same seed -> identical op stream (replayable load profile)
    g1, g2 = LoadGenerator(net, "issuer", seed=5), \
        LoadGenerator(net, "issuer", seed=5)
    assert [g1._pick_op() for _ in range(30)] == \
        [g2._pick_op() for _ in range(30)]
    # a different seed produces a different stream
    g3 = LoadGenerator(net, "issuer", seed=6)
    assert [g3._pick_op() for _ in range(30)] != \
        [LoadGenerator(net, "issuer", seed=5)._pick_op()
         for _ in range(30)]


def test_concurrent_load_conserves_balances(net):
    """Stress shape: 4 workers race on the selector; failures are allowed
    (lock contention) but balances must stay conserved and non-negative."""
    gen = LoadGenerator(net, "issuer",
                        profile=TxProfile(issue_weight=0.3,
                                          transfer_weight=0.6,
                                          redeem_weight=0.1),
                        seed=23)
    report = gen.run(60, parallelism=4, bootstrap_value=300)
    assert report.succeeded > 0
    total = sum(u.balance("USD") for u in net)
    issued = sum(1 for o in report.outcomes if o.ok and o.op == "issue")
    assert total >= 0
    # every token ever visible is accounted for: replay the audit trail
    auditor = net[0].bus.node("auditor")
    recs = auditor.auditdb.query_transactions()
    minted = sum(r.amount for r in recs if r.action_type == "issue"
                 and r.status == "Confirmed")
    burned = sum(r.amount for r in recs if r.action_type == "redeem"
                 and r.status == "Confirmed")
    assert total == minted - burned


def test_empty_wallet_failures_reported(net):
    gen = LoadGenerator(net, "issuer",
                        profile=TxProfile(issue_weight=0.0,
                                          transfer_weight=1.0,
                                          redeem_weight=0.0),
                        seed=2)
    report = gen.run(5)  # no bootstrap: every transfer must fail
    assert report.failed == 5
    assert "InsufficientFunds" in report.failures_by_error()
