"""Device/oracle parity across ALL supported bit lengths (VERDICT r1 #2).

The headline config is 64-bit; round-1 only pinned 16-bit. Each bit length
gets the same adversarial matrix: valid proofs at the value-domain edges, a
tamper per transcript-relevant component, a wrong-statement commitment, and
valid/invalid interleavings at batch-bucket boundaries.

Compile note: the 32/64-bit kernels trace fresh XLA executables on first
run (minutes on CPU); the persistent cache makes every later run cheap.
"""

import random

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier

rng = random.Random(0xD1CE)


@pytest.fixture(scope="module", params=[16, 32, 64])
def world(request):
    n = request.param
    pp = setup.setup(n)
    return dict(n=n, pp=pp, verifier=BatchRangeVerifier(pp))


def _prove_one(pp, value):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    bf = bn254.fr_rand()
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length)
    return proof, com


def _oracle_ok(pp, proof, com):
    rpp = pp.range_proof_params
    try:
        rp.range_verify(proof, com, pp.pedersen_generators[1:3],
                        rpp.left_generators, rpp.right_generators,
                        rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        return True
    except rp.ProofError:
        return False


def test_parity_with_adversarial_matrix(world):
    n, pp, verifier = world["n"], world["pp"], world["verifier"]
    proofs, coms = [], []

    # valid proofs at the value-domain edges + a random interior point
    for v in [0, 1, (1 << n) - 1, rng.randrange(1 << n)]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)

    # tamper matrix: one mutation per transcript-relevant component
    t0, c0 = _prove_one(pp, 99)
    t0.data.tau = bn254.fr_add(t0.data.tau, 1)
    proofs.append(t0); coms.append(c0)

    t1, c1 = _prove_one(pp, 100)
    t1.data.T2 = bn254.g1_add(t1.data.T2, bn254.G1_GENERATOR)
    proofs.append(t1); coms.append(c1)

    t2, c2 = _prove_one(pp, 101)
    t2.ipa.right = bn254.fr_add(t2.ipa.right, 1)
    proofs.append(t2); coms.append(c2)

    t3, c3 = _prove_one(pp, 102)
    t3.ipa.R[-1] = bn254.g1_add(t3.ipa.R[-1], bn254.G1_GENERATOR)
    proofs.append(t3); coms.append(c3)

    # wrong statement: valid proof against someone else's commitment
    t4, _ = _prove_one(pp, 103)
    _, cwrong = _prove_one(pp, 104)
    proofs.append(t4); coms.append(cwrong)

    got = verifier.verify(proofs, coms)
    want = np.array([_oracle_ok(pp, pf, cm)
                     for pf, cm in zip(proofs, coms)])
    assert want[:4].all() and not want[4:].any()  # oracle sanity
    assert (got == want).all(), \
        f"n={n}: device {got.tolist()} != oracle {want.tolist()}"


def test_parity_interleaved_at_bucket_boundary(world):
    """Valid/invalid interleavings crossing the batch-bucket edge (8):
    catches batch-position bugs the tiled bench can't see.

    Shape note: the boundary exercised is 8 -> 16 rows, not 16 -> 32.
    The crossing logic is identical, and the 32-row kernel variants sit
    in the executable size class whose in-process accumulation triggers
    a known jaxlib XLA:CPU native crash (see utils/jaxcfg
    install_cache_size_guard) — staying inside the proven 16-row
    envelope keeps this suite deterministic everywhere."""
    n, pp, verifier = world["n"], world["pp"], world["verifier"]

    base = []
    for v in (5, 6, 7, 8):
        base.append(_prove_one(pp, v))
    bad_pf, bad_com = _prove_one(pp, 9)
    bad_pf.data.delta = bn254.fr_add(bad_pf.data.delta, 1)

    # 12 entries: spills past the 8-row bucket; invalid at positions
    # 0, 7, 8 (start / last-of-bucket / first-of-next)
    proofs, coms, expect = [], [], []
    for i in range(12):
        if i in (0, 7, 8):
            proofs.append(bad_pf); coms.append(bad_com); expect.append(False)
        else:
            pf, com = base[i % 4]
            proofs.append(pf); coms.append(com); expect.append(True)

    got = verifier.verify(proofs, coms)
    assert got.tolist() == expect, f"n={n}: {got.tolist()} != {expect}"


def test_exact_path_matches_combined_accepts(world):
    """exact=True (per-proof checks) agrees with the RLC fast path."""
    n, pp, verifier = world["n"], world["pp"], world["verifier"]
    proofs, coms = [], []
    for v in (11, 22):
        pf, com = _prove_one(pp, v)
        proofs.append(pf); coms.append(com)
    fast = verifier.verify(proofs, coms)
    assert verifier.last_path == "combined"
    exact = verifier.verify(proofs, coms, exact=True)
    assert verifier.last_path == "exact"
    assert fast.tolist() == exact.tolist() == [True, True]


def test_multichunk_pipeline_bisect(monkeypatch):
    """The chunked pipeline + per-chunk RLC bisect (production path for
    B > FTS_VERIFY_CHUNK): corrupted proofs in NON-first chunks must be
    isolated, clean chunks must keep their combined-accept verdicts."""
    from fabric_token_sdk_tpu.models import range_verifier as rv

    monkeypatch.setattr(rv, "_CHUNK_ROWS", 2)
    pp = setup.setup(16)
    verifier = BatchRangeVerifier(pp)
    proofs, coms = [], []
    for i in range(6):
        p, c = _prove_one(pp, 3 + i)
        proofs.append(p)
        coms.append(c)
    out = verifier.verify(proofs, coms)     # 3 chunks, all clean
    assert out.all() and verifier.last_path == "combined"

    # corrupt one proof in chunk 2 (index 3): bisect isolates that chunk
    proofs[3].data.tau = (proofs[3].data.tau + 1) % bn254.R
    out = verifier.verify(proofs, coms)
    assert list(out) == [True, True, True, False, True, True]
    assert verifier.last_path == "exact"
    # oracle agreement on the corrupted row
    assert not _oracle_ok(pp, proofs[3], coms[3])
