"""End-to-end zkatdlog slice: ZK issue -> ZK transfer through the validator.

The full SURVEY.md §3.2 pipeline with real proofs: commitment tokens,
same-type + range proofs on issue, type-and-sum + range proofs on transfer,
owner/issuer/auditor signatures, RW-set translation — with the range proofs
verified in a single TPU batch behind the validator boundary (device=True on
the CPU test mesh).
"""

import pytest

from fabric_token_sdk_tpu.core import zkatdlog
from fabric_token_sdk_tpu.core.zkatdlog.actions import (ActionInput,
                                                        IssueAction, Token,
                                                        TransferAction)
from fabric_token_sdk_tpu.crypto import bn254, issue_proof, setup, token_commit, \
    transfer_proof
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.token.model import ID

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def world():
    issuer = new_signing_identity()
    auditor = new_signing_identity()
    alice = new_signing_identity()
    bob = new_signing_identity()
    pp = setup.setup(BIT_LENGTH)
    pp.add_issuer(bytes(issuer.identity))
    pp.add_auditor(bytes(auditor.identity))
    validator = zkatdlog.new_validator(pp, Deserializer(), device=True)
    ledger = MemoryLedger()
    cc = TokenChaincode(validator, ledger, pp.serialize())
    return dict(pp=pp, cc=cc, issuer=issuer, auditor=auditor, alice=alice,
                bob=bob)


def _signed(world, tx_id, issues=(), transfers=(), signers=()):
    req = TokenRequest(issues=[a.serialize() for a in issues],
                       transfers=[a.serialize() for a in transfers])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    req.signatures = [s.sign(msg) for s in signers]
    return req


def _issue(world, tx_id, values, owner):
    pp = world["pp"]
    coms, wits = token_commit.get_tokens_with_witness(
        values, "USD", pp.pedersen_generators)
    proof = issue_proof.issue_prove([w.as_tuple() for w in wits], coms, pp)
    action = IssueAction(
        issuer=world["issuer"].identity,
        outputs=[Token(owner=bytes(owner.identity), data=c) for c in coms],
        proof=proof,
    )
    req = _signed(world, tx_id, issues=[action], signers=[world["issuer"]])
    ev = world["cc"].process_request(tx_id, req.to_bytes())
    return ev, action, wits


def test_zk_issue_and_transfer(world):
    pp = world["pp"]
    alice, bob = world["alice"], world["bob"]
    ev, issue_action, wits = _issue(world, "ztx1", [600, 400], alice)
    assert ev.status == "VALID", ev.message

    # transfer: spend both outputs -> 900 to bob, 100 change to alice
    in_tokens = issue_action.outputs
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [900, 100], "USD", pp.pedersen_generators)
    proof = transfer_proof.transfer_prove(
        [w.as_tuple() for w in wits], [w.as_tuple() for w in out_wits],
        [t.data for t in in_tokens], out_coms, pp)
    action = TransferAction(
        inputs=[ActionInput(id=ID("ztx1", i), token=in_tokens[i])
                for i in range(2)],
        outputs=[Token(owner=bytes(bob.identity), data=out_coms[0]),
                 Token(owner=bytes(alice.identity), data=out_coms[1])],
        proof=proof,
    )
    req = _signed(world, "ztx2", transfers=[action], signers=[alice, alice])
    ev = world["cc"].process_request("ztx2", req.to_bytes())
    assert ev.status == "VALID", ev.message

    # inputs burnt on ledger
    assert world["cc"].are_tokens_spent([ID("ztx1", 0), ID("ztx1", 1)]) == \
        [True, True]

    # double spend rejected
    req2 = _signed(world, "ztx3", transfers=[action], signers=[alice, alice])
    ev = world["cc"].process_request("ztx3", req2.to_bytes())
    assert ev.status == "INVALID"


def test_unbalanced_zk_transfer_rejected(world):
    """Prover cheats: outputs sum to more than inputs -> proof fails."""
    pp = world["pp"]
    alice, bob = world["alice"], world["bob"]
    ev, issue_action, wits = _issue(world, "ztx4", [50], alice)
    assert ev.status == "VALID", ev.message

    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [60, 5], "USD", pp.pedersen_generators)
    # honest prove fails the sigma protocol only at verify time, so craft the
    # proof against *claimed* input value 65 (lying about the opening).
    lying_wits = [("USD", 65, wits[0].blinding_factor)]
    proof = transfer_proof.transfer_prove(
        lying_wits, [w.as_tuple() for w in out_wits],
        [issue_action.outputs[0].data], out_coms, pp)
    action = TransferAction(
        inputs=[ActionInput(id=ID("ztx4", 0), token=issue_action.outputs[0])],
        outputs=[Token(owner=bytes(bob.identity), data=out_coms[0]),
                 Token(owner=bytes(alice.identity), data=out_coms[1])],
        proof=proof,
    )
    req = _signed(world, "ztx5", transfers=[action], signers=[alice])
    ev = world["cc"].process_request("ztx5", req.to_bytes())
    assert ev.status == "INVALID"
    assert "proof" in ev.message


def test_out_of_range_output_rejected(world):
    """Output value >= 2^BitLength must fail the range proof."""
    pp = world["pp"]
    alice, bob = world["alice"], world["bob"]
    big = (1 << BIT_LENGTH)  # one past the max
    ev, issue_action, wits = _issue(world, "ztx6", [3, 2], alice)
    assert ev.status == "VALID", ev.message
    # outputs: big and (5 - big) mod r -> sums match mod r, range must catch
    out_vals = [big, (5 - big) % bn254.R]
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        out_vals, "USD", pp.pedersen_generators)
    proof = transfer_proof.transfer_prove(
        [w.as_tuple() for w in wits], [w.as_tuple() for w in out_wits],
        [t.data for t in issue_action.outputs], out_coms, pp)
    action = TransferAction(
        inputs=[ActionInput(id=ID("ztx6", i), token=issue_action.outputs[i])
                for i in range(2)],
        outputs=[Token(owner=bytes(bob.identity), data=out_coms[0]),
                 Token(owner=bytes(alice.identity), data=out_coms[1])],
        proof=proof,
    )
    req = _signed(world, "ztx7", transfers=[action], signers=[alice, alice])
    ev = world["cc"].process_request("ztx7", req.to_bytes())
    assert ev.status == "INVALID"
    assert "range" in ev.message or "proof" in ev.message


def test_one_in_one_out_skips_range(world):
    """1-in/1-out ownership transfer has no range proofs
    (transfer.go:53-57,101-112)."""
    pp = world["pp"]
    alice, bob = world["alice"], world["bob"]
    ev, issue_action, wits = _issue(world, "ztx8", [77], alice)
    assert ev.status == "VALID", ev.message
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [77], "USD", pp.pedersen_generators)
    proof = transfer_proof.transfer_prove(
        [w.as_tuple() for w in wits], [w.as_tuple() for w in out_wits],
        [issue_action.outputs[0].data], out_coms, pp)
    parsed = transfer_proof.TransferProof.deserialize(proof)
    assert not parsed.range_correctness.proofs  # skipped for 1-1
    action = TransferAction(
        inputs=[ActionInput(id=ID("ztx8", 0), token=issue_action.outputs[0])],
        outputs=[Token(owner=bytes(bob.identity), data=out_coms[0])],
        proof=proof,
    )
    req = _signed(world, "ztx9", transfers=[action], signers=[alice])
    ev = world["cc"].process_request("ztx9", req.to_bytes())
    assert ev.status == "VALID", ev.message
