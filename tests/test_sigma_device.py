"""Device-batched Σ-protocol verification vs the host oracle.

Covers models/sigma.py against crypto/transfer_proof.type_and_sum_verify
and crypto/issue_proof.same_type_verify (reference typeandsum.go:230-277,
sametype.go:167-183): same accept/reject on valid proofs, tampered
responses, wrong challenges, and mixed batches.
"""

import pytest

from fabric_token_sdk_tpu.crypto import bn254, setup
from fabric_token_sdk_tpu.crypto import issue_proof as ip
from fabric_token_sdk_tpu.crypto import transfer_proof as tp
from fabric_token_sdk_tpu.crypto.bn254 import (fr_rand, fr_sub, g1_add,
                                               g1_mul, hash_to_zr)
from fabric_token_sdk_tpu.models.sigma import BatchSigmaVerifier

BIT = 16


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT)


@pytest.fixture(scope="module")
def sigma(pp):
    return BatchSigmaVerifier(pp)


def _make_transfer(pp, n_in=2, n_out=2, value=20):
    ped = pp.pedersen_generators
    token_type = "USD"
    type_zr = hash_to_zr(token_type.encode())
    type_bf = fr_rand()
    ctt = g1_add(g1_mul(ped[0], type_zr), g1_mul(ped[2], type_bf))
    in_vals = [value] * n_in
    out_vals = [value * n_in // n_out] * n_out
    in_bfs = [fr_rand() for _ in range(n_in)]
    out_bfs = [fr_rand() for _ in range(n_out)]
    from fabric_token_sdk_tpu.crypto import token_commit

    inputs = [token_commit.commit_token(token_type, v, bf, ped)
              for v, bf in zip(in_vals, in_bfs)]
    outputs = [token_commit.commit_token(token_type, v, bf, ped)
               for v, bf in zip(out_vals, out_bfs)]
    proof = tp.type_and_sum_prove(ped, inputs, outputs, ctt, in_vals,
                                  in_bfs, out_bfs, type_zr, type_bf)
    return proof, inputs, outputs


def _make_same_type(pp):
    ped = pp.pedersen_generators
    type_bf = fr_rand()
    type_zr = hash_to_zr(b"USD")
    ctt = g1_add(g1_mul(ped[0], type_zr), g1_mul(ped[2], type_bf))
    return ip.same_type_prove("USD", type_bf, ctt, ped)


class TestTypeAndSumDevice:
    def test_valid_batch_accepts(self, pp, sigma):
        items = [_make_transfer(pp, n_in=1 + (i % 3), n_out=2)
                 for i in range(5)]
        accepts = sigma.verify_type_and_sum(items)
        assert accepts.all()
        # host oracle agrees item by item
        for proof, inputs, outputs in items:
            tp.type_and_sum_verify(proof, pp.pedersen_generators, inputs,
                                   outputs)

    def test_tampered_entries_rejected_only(self, pp, sigma):
        items = [_make_transfer(pp) for _ in range(4)]
        # tamper item 1's response and item 3's challenge
        items[1][0].equality_of_sum = fr_sub(items[1][0].equality_of_sum, 1)
        items[3][0].challenge = fr_sub(items[3][0].challenge, 1)
        accepts = sigma.verify_type_and_sum(items)
        assert list(accepts) == [True, False, True, False]
        for i in (1, 3):
            with pytest.raises(tp.ProofError):
                tp.type_and_sum_verify(items[i][0], pp.pedersen_generators,
                                       items[i][1], items[i][2])

    def test_wrong_value_response_rejected(self, pp, sigma):
        proof, inputs, outputs = _make_transfer(pp)
        proof.input_values[0] = fr_sub(proof.input_values[0], 1)
        accepts = sigma.verify_type_and_sum([(proof, inputs, outputs)])
        assert not accepts[0]

    def test_structural_nils_rejected(self, pp, sigma):
        proof, inputs, outputs = _make_transfer(pp)
        proof.type_ = None
        accepts = sigma.verify_type_and_sum([(proof, inputs, outputs)])
        assert not accepts[0]
        accepts = sigma.verify_type_and_sum([(None, inputs, outputs)])
        assert not accepts[0]

    def test_short_response_vectors_rejected(self, pp, sigma):
        proof, inputs, outputs = _make_transfer(pp, n_in=2)
        proof.input_values = proof.input_values[:1]
        accepts = sigma.verify_type_and_sum([(proof, inputs, outputs)])
        assert not accepts[0]


class TestVerifyBlock:
    """ZKVerifier.verify_block: mixed Issue+Transfer block, one device
    pass for all Σ checks + one for all range proofs (config 3 shape)."""

    @pytest.fixture(scope="class")
    def zk(self, pp):
        from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier

        return ZKVerifier(pp, device=True)

    def _transfer_raw(self, pp, tamper=None):
        from fabric_token_sdk_tpu.crypto import token_commit

        ped = pp.pedersen_generators
        in_bfs = [fr_rand(), fr_rand()]
        out_bfs = [fr_rand(), fr_rand()]
        inputs = [token_commit.commit_token("USD", 10, bf, ped)
                  for bf in in_bfs]
        outputs = [token_commit.commit_token("USD", 10, bf, ped)
                   for bf in out_bfs]
        raw = tp.transfer_prove(
            [("USD", 10, bf) for bf in in_bfs],
            [("USD", 10, bf) for bf in out_bfs], inputs, outputs, pp)
        if tamper == "sigma":
            p = tp.TransferProof.deserialize(raw)
            p.type_and_sum.equality_of_sum = fr_sub(
                p.type_and_sum.equality_of_sum, 1)
            raw = p.serialize()
        elif tamper == "range":
            p = tp.TransferProof.deserialize(raw)
            p.range_correctness.proofs[0].data.tau = fr_sub(
                p.range_correctness.proofs[0].data.tau, 1)
            raw = p.serialize()
        return raw, inputs, outputs

    def _issue_raw(self, pp):
        from fabric_token_sdk_tpu.crypto import token_commit

        ped = pp.pedersen_generators
        bfs = [fr_rand(), fr_rand()]
        toks = [token_commit.commit_token("EUR", 7, bf, ped) for bf in bfs]
        raw = ip.issue_prove([("EUR", 7, bf) for bf in bfs], toks, pp)
        return raw, toks

    def test_mixed_block_accepts_and_isolates_rejects(self, pp, zk):
        transfers = [self._transfer_raw(pp),
                     self._transfer_raw(pp, tamper="sigma"),
                     self._transfer_raw(pp, tamper="range")]
        issues = [self._issue_raw(pp), (b"garbage", [])]
        t_ok, i_ok = zk.verify_block(transfers, issues)
        assert list(t_ok) == [True, False, False]
        assert list(i_ok) == [True, False]
        # per-action APIs agree on the rejects (exact-error path)
        from fabric_token_sdk_tpu.crypto.rp import ProofError

        zk.verify_transfer(*transfers[0])
        with pytest.raises(ProofError):
            zk.verify_transfer(*transfers[1])
        with pytest.raises(ProofError):
            zk.verify_transfer(*transfers[2])

    def test_block_host_fallback_matches(self, pp):
        from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier

        host = ZKVerifier(pp, device=False)
        transfers = [self._transfer_raw(pp),
                     self._transfer_raw(pp, tamper="sigma")]
        t_ok, i_ok = host.verify_block(transfers, [])
        assert list(t_ok) == [True, False]
        assert i_ok.shape == (0,)


class TestPerRequestSigmaOnDevice:
    """VERDICT r3 #4: the per-request validator path (verify_transfer /
    verify_issue) runs its Σ scalar-muls on device; the host oracle is
    reached only to reproduce reject error messages."""

    @pytest.fixture(scope="class")
    def zk(self, pp):
        from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier

        return ZKVerifier(pp, device=True)

    def _transfer_raw(self, pp, tamper=None):
        from fabric_token_sdk_tpu.crypto import token_commit

        ped = pp.pedersen_generators
        in_bfs, out_bfs = [fr_rand(), fr_rand()], [fr_rand(), fr_rand()]
        inputs = [token_commit.commit_token("USD", 10, bf, ped)
                  for bf in in_bfs]
        outputs = [token_commit.commit_token("USD", 10, bf, ped)
                   for bf in out_bfs]
        raw = tp.transfer_prove(
            [("USD", 10, bf) for bf in in_bfs],
            [("USD", 10, bf) for bf in out_bfs], inputs, outputs, pp)
        if tamper == "sigma":
            p = tp.TransferProof.deserialize(raw)
            p.type_and_sum.equality_of_sum = fr_sub(
                p.type_and_sum.equality_of_sum, 1)
            raw = p.serialize()
        return raw, inputs, outputs

    def test_accept_path_never_calls_host_sigma(self, pp, zk, monkeypatch):
        raw, inputs, outputs = self._transfer_raw(pp)

        def boom(*a, **k):
            raise AssertionError("host Σ oracle reached on the accept path")

        monkeypatch.setattr(tp, "type_and_sum_verify", boom)
        zk.verify_transfer(raw, inputs, outputs)  # must not raise

    def test_issue_accept_path_never_calls_host_sigma(self, pp, zk,
                                                      monkeypatch):
        from fabric_token_sdk_tpu.crypto import token_commit

        ped = pp.pedersen_generators
        bfs = [fr_rand(), fr_rand()]
        toks = [token_commit.commit_token("EUR", 7, bf, ped) for bf in bfs]
        raw = ip.issue_prove([("EUR", 7, bf) for bf in bfs], toks, pp)

        def boom(*a, **k):
            raise AssertionError("host Σ oracle reached on the accept path")

        monkeypatch.setattr(ip, "same_type_verify", boom)
        zk.verify_issue(raw, toks)  # must not raise

    def test_sigma_reject_reproduces_host_error(self, pp, zk):
        from fabric_token_sdk_tpu.crypto.rp import ProofError

        raw, inputs, outputs = self._transfer_raw(pp, tamper="sigma")
        with pytest.raises(ProofError, match="invalid transfer proof"):
            zk.verify_transfer(raw, inputs, outputs)

    def test_range_reverify_touches_only_rejected_rows(self, pp, zk,
                                                       monkeypatch):
        """VERDICT r3 #5: the host re-verify tail is O(#invalid), not
        O(tail-from-first-bad)."""
        from fabric_token_sdk_tpu.crypto import rp as rp_mod
        from fabric_token_sdk_tpu.crypto.rp import ProofError

        raw, inputs, outputs = self._transfer_raw(pp)
        p = tp.TransferProof.deserialize(raw)
        # tamper output 0's range proof only; output 1's stays valid
        p.range_correctness.proofs[0].data.tau = fr_sub(
            p.range_correctness.proofs[0].data.tau, 1)
        raw = p.serialize()

        calls = []
        host_verify = rp_mod.range_verify

        def counting(proof, com, *a, **k):
            calls.append(proof)
            return host_verify(proof, com, *a, **k)

        monkeypatch.setattr(rp_mod, "range_verify", counting)
        with pytest.raises(ProofError, match="invalid range proof at index 0"):
            zk.verify_transfer(raw, inputs, outputs)
        # exactly the one rejected row re-verified on host, not the tail
        assert len(calls) == 1


class TestSameTypeDevice:
    def test_valid_and_tampered_mixed(self, pp, sigma):
        proofs = [_make_same_type(pp) for _ in range(4)]
        proofs[2].blinding_factor = fr_sub(proofs[2].blinding_factor, 1)
        accepts = sigma.verify_same_type(proofs)
        assert list(accepts) == [True, True, False, True]
        with pytest.raises(ip.ProofError):
            ip.same_type_verify(proofs[2], pp.pedersen_generators)
        ip.same_type_verify(proofs[0], pp.pedersen_generators)

    def test_nil_fields_rejected(self, pp, sigma):
        p = _make_same_type(pp)
        p.challenge = None
        accepts = sigma.verify_same_type([p, None])
        assert not accepts.any()

    def test_empty_batch(self, sigma):
        assert sigma.verify_same_type([]).shape == (0,)
        assert sigma.verify_type_and_sum([]).shape == (0,)
