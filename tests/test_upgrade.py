"""Token upgrade across a public-params update: fabtoken -> zkatdlog.

The reference's TestPublicParamsUpdate scenario (fungible/dlog/dlog_test.go
:50-58 + zkatdlog v1/tokens.go:208-284, validator_transfer.go:64-93): a
network switches drivers; plaintext tokens already on the ledger are spent
under the NEW zkatdlog pp by attaching upgrade witnesses that bind fresh
commitments to the old plaintext.
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken, zkatdlog
from fabric_token_sdk_tpu.core.zkatdlog.actions import (ActionInput, Token,
                                                        TransferAction,
                                                        UpgradeWitness)
from fabric_token_sdk_tpu.core.zkatdlog.driver import ZkDlogDriverService
from fabric_token_sdk_tpu.crypto import bn254, setup as zk_setup, \
    token_commit, transfer_proof
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus
from fabric_token_sdk_tpu.token.model import ID

BIT_LENGTH = 16


@pytest.fixture
def world():
    """Phase 1: a fabtoken network issues plaintext tokens. Phase 2: the
    pp update swaps in the zkatdlog validator over the SAME ledger."""
    issuer, auditor = new_signing_identity(), new_signing_identity()
    alice, bob = new_signing_identity(), new_signing_identity()

    fab_pp = fabtoken.setup(BIT_LENGTH)
    fab_pp.issuer_ids = [issuer.identity]
    fab_pp.auditor = bytes(auditor.identity)
    ledger = MemoryLedger()
    fab_cc = TokenChaincode(fabtoken.new_validator(fab_pp, Deserializer()),
                            ledger, fab_pp.serialize())

    # issue plaintext 77 USD to alice under the OLD pp
    issue = fabtoken.IssueAction(
        issuer=issuer.identity,
        outputs=[fabtoken.Output(bytes(alice.identity), "USD", "0x4d")])
    req = TokenRequest(issues=[issue.serialize()])
    msg = req.message_to_sign(b"old1")
    req.auditor_signatures = [auditor.sign(msg)]
    req.signatures = [issuer.sign(msg)]
    assert fab_cc.process_request("old1", req.to_bytes()).status == "VALID"

    # pp UPDATE: same ledger, new validator + pp (TMSProvider.Update role)
    zk_pp = zk_setup.setup(BIT_LENGTH)
    zk_pp.issuer_ids = [issuer.identity]
    zk_pp.auditor = bytes(auditor.identity)
    zk_cc = TokenChaincode(
        zkatdlog.new_validator(zk_pp, Deserializer(), device=False),
        ledger, zk_pp.serialize())
    return dict(zk_pp=zk_pp, zk_cc=zk_cc, issuer=issuer, auditor=auditor,
                alice=alice, bob=bob, fab_out=issue.outputs[0])


def _upgrade_transfer(world, bf=None, claim_value=None, owner=None):
    """Build the upgrade spend: old plaintext token -> new commitments."""
    pp = world["zk_pp"]
    alice, bob = world["alice"], world["bob"]
    value = claim_value if claim_value is not None else 0x4d
    bf = bf if bf is not None else bn254.fr_rand()
    owner = owner if owner is not None else bytes(alice.identity)
    com = token_commit.commit_token("USD", value, bf,
                                    pp.pedersen_generators)
    witness = UpgradeWitness(owner=bytes(world["fab_out"].owner),
                             token_type="USD", quantity="0x4d",
                             blinding_factor=bf)
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [0x4d], "USD", pp.pedersen_generators)
    proof = transfer_proof.transfer_prove(
        [("USD", value, bf)], [w.as_tuple() for w in out_wits],
        [com], out_coms, pp)
    action = TransferAction(
        inputs=[ActionInput(id=ID("old1", 0),
                            token=Token(owner=owner, data=com),
                            upgrade_witness=witness)],
        outputs=[Token(owner=bytes(bob.identity), data=out_coms[0])],
        proof=proof,
    )
    return action


def _submit(world, tx_id, action, signer):
    req = TokenRequest(transfers=[action.serialize()])
    msg = req.message_to_sign(tx_id.encode())
    req.auditor_signatures = [world["auditor"].sign(msg)]
    req.signatures = [signer.sign(msg)]
    return world["zk_cc"].process_request(tx_id, req.to_bytes())


def test_upgrade_spend_accepted(world):
    action = _upgrade_transfer(world)
    ev = _submit(world, "up1", action, world["alice"])
    assert ev.status == "VALID", ev.message
    # the plaintext token is spent; the commitment output is live
    assert world["zk_cc"].are_tokens_spent([ID("old1", 0)]) == [True]

    # wire round trip preserves the witness
    restored = TransferAction.deserialize(action.serialize())
    assert restored.inputs[0].upgrade_witness.quantity == "0x4d"
    assert restored.serialize() == action.serialize()


def test_upgrade_wrong_value_rejected(world):
    """Witness claims 0x4d but the commitment holds a different value."""
    action = _upgrade_transfer(world, claim_value=0x4e)
    ev = _submit(world, "up2", action, world["alice"])
    assert ev.status == "INVALID"
    assert "commitment does not match" in ev.message


def test_upgrade_wrong_owner_rejected(world):
    """Claimed input owner (bob, who also signs) differs from the witness's
    plaintext owner (alice): the witness step must reject."""
    action = _upgrade_transfer(world, owner=bytes(world["bob"].identity))
    ev = _submit(world, "up3", action, world["bob"])
    assert ev.status == "INVALID"
    assert "owners do not correspond" in ev.message


def test_upgrade_nonexistent_ledger_token_rejected(world):
    """A witness for plaintext that is NOT on the ledger cannot commit."""
    action = _upgrade_transfer(world)
    action.inputs[0].upgrade_witness.quantity = "0x10"  # ledger holds 0x4d
    # recompute commitment/proof consistently with the lie
    bf = action.inputs[0].upgrade_witness.blinding_factor
    pp = world["zk_pp"]
    com = token_commit.commit_token("USD", 0x10, bf,
                                    pp.pedersen_generators)
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        [0x10], "USD", pp.pedersen_generators)
    proof = transfer_proof.transfer_prove(
        [("USD", 0x10, bf)], [w.as_tuple() for w in out_wits],
        [com], out_coms, pp)
    action.inputs[0].token = Token(owner=bytes(world["alice"].identity),
                                   data=com)
    action.outputs = [Token(owner=bytes(world["bob"].identity),
                            data=out_coms[0])]
    action.proof = proof
    ev = _submit(world, "up4", action, world["alice"])
    assert ev.status == "INVALID"
    assert "input must exist" in ev.message


def test_upgrade_through_node_services(world):
    """The full services path: a zkatdlog node ingests the OLD plaintext
    token from the ledger scan and spends it with an auto-built witness."""
    pp, cc = world["zk_pp"], world["zk_cc"]
    bus = SessionBus()
    driver = ZkDlogDriverService(pp, device=False)
    alice_node = TokenNode("alice", world["alice"], bus, cc,
                           precision=BIT_LENGTH, auditor_name="auditor",
                           driver=driver)
    TokenNode("issuer", world["issuer"], bus, cc, precision=BIT_LENGTH,
              auditor_name="auditor", driver=driver)
    AuditorNode("auditor", world["auditor"], bus, cc,
                precision=BIT_LENGTH, auditor_name="auditor", driver=driver)
    bob_node = TokenNode("bob", new_signing_identity(), bus, cc,
                         precision=BIT_LENGTH, auditor_name="auditor",
                         driver=driver)

    # scan the ledger: the plaintext token ingests in the clear
    alice_node._ingest_from_ledger("old1", {}, 1)
    assert alice_node.balance("USD") == 0x4d

    # spend it: the driver detects the fabtoken format and upgrades
    tx = alice_node.transfer("USD", hex(0x20), "bob")
    ev = alice_node.execute(tx)
    assert ev.status == "VALID", ev.message
    assert bob_node.balance("USD") == 0x20
    assert alice_node.balance("USD") == 0x4d - 0x20
