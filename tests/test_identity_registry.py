"""Role-based wallet registry / local membership / recipient registration.

Mirrors reference token/services/identity/{role,wallet}: role.go
MapToIdentity resolution order, wallet registry lookup + BindIdentity,
service.go RegisterRecipientIdentity with audit-info matching.
"""

import pytest

from fabric_token_sdk_tpu.services.db.sqldb import IdentityDB
from fabric_token_sdk_tpu.services.identity.idemix import (
    EnrollmentAuthority,
    IdemixInfoMatcher,
    IdemixKeyManager,
)
from fabric_token_sdk_tpu.services.identity.registry import (
    LocalMembership,
    RegistryError,
    Role,
    RoleType,
    WalletService,
)
from fabric_token_sdk_tpu.services.identity.wallet import (
    IdemixOwnerWallet,
    X509OwnerWallet,
)
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity


def _ws():
    keys = new_signing_identity()
    ws = WalletService.for_node("alice", keys, IdentityDB(":memory:"))
    return keys, ws


def test_role_lookup_resolution_order():
    keys = new_signing_identity()
    wallet = X509OwnerWallet(keys)
    m = LocalMembership()
    m.register("alice", wallet, enrollment_id="alice-eid")
    role = Role(RoleType.OWNER, m)

    # empty lookup -> default wallet (role.go: empty label)
    assert role.map_to_identifier(None) == "alice"
    assert role.map_to_identifier("") == "alice"
    # label -> itself; owned identity bytes -> its label
    assert role.map_to_identifier("alice") == "alice"
    assert role.map_to_identifier(bytes(keys.identity)) == "alice"
    # unknown -> None
    assert role.map_to_identifier("nobody") is None
    assert role.map_to_identifier(b"\x01\x02") is None


def test_wallet_service_roles_and_default():
    keys, ws = _ws()
    assert ws.owner_wallet() is ws.owner_wallet("alice")
    assert ws.issuer_wallet().keys is keys
    assert ws.auditor_wallet().owns(bytes(keys.identity))
    assert ws.certifier_wallet() is not None
    with pytest.raises(RegistryError):
        ws.owner_wallet("bob")
    assert ws.wallet_ids(RoleType.OWNER) == ["alice"]


def test_multiple_owner_wallets_and_bindings():
    keys, ws = _ws()
    km = IdemixKeyManager("alice-eid", EnrollmentAuthority())
    ws.register_owner_wallet("alice.anon", IdemixOwnerWallet(km),
                             enrollment_id="alice-eid")
    assert set(ws.wallet_ids(RoleType.OWNER)) == {"alice", "alice.anon"}

    anon = ws.owner_wallet("alice.anon")
    nym, audit_info = anon.recipient_identity()
    # a fresh pseudonym resolves through the wallet that controls it
    assert ws.owner_wallet(nym) is anon

    reg = ws.registries[RoleType.OWNER]
    reg.bind_identity(nym, "alice-eid", "alice.anon", audit_info)
    assert reg.contains_identity(nym)
    assert reg.contains_identity(nym, "alice.anon")
    assert not reg.contains_identity(nym, "alice")
    assert ws.get_audit_info(nym) == audit_info


def test_register_recipient_identity_matches_audit_info():
    authority = EnrollmentAuthority()
    km = IdemixKeyManager("bob-eid", authority)
    matcher = IdemixInfoMatcher(authority.ca_identity())
    ws = WalletService(IdentityDB(":memory:"), info_matcher=matcher)

    nym, audit_info = IdemixOwnerWallet(km).recipient_identity()
    ws.register_recipient_identity(nym, audit_info)
    assert ws.get_audit_info(nym) == audit_info

    # mismatched audit info is rejected (service.go MatchIdentity)
    other_nym, other_ai = IdemixOwnerWallet(km).recipient_identity()
    with pytest.raises(Exception):
        ws.register_recipient_identity(nym, other_ai)


def test_identity_db_persistence():
    keys = new_signing_identity()
    db = IdentityDB(":memory:")
    WalletService.for_node("alice", keys, db)
    # long-term wallets are persisted for restart recovery
    assert db.wallet_identity("alice", RoleType.OWNER) == bytes(keys.identity)
    assert db.wallet_identity("alice", RoleType.ISSUER) == bytes(keys.identity)


def test_node_exposes_wallet_manager():
    from fabric_token_sdk_tpu.core import fabtoken
    from fabric_token_sdk_tpu.services.identity.deserializer import \
        Deserializer
    from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
        TokenChaincode
    from fabric_token_sdk_tpu.services.node import TokenNode
    from fabric_token_sdk_tpu.services.ttx import SessionBus

    keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [keys.identity]
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    node = TokenNode("alice", keys, SessionBus(), cc)
    # the registry resolves to the SAME active owner-wallet object
    assert node.wallets.owner_wallet() is node.owner_wallet
    assert node.wallets.owner_wallet(bytes(keys.identity)) is node.owner_wallet
