"""Device-batched point adjustment vs the host oracle.

models/adjust.py routes the verifiers' commitment adjustments
(out - com_type; reference crypto/transfer/transfer.go:176-180,
crypto/issue/verifier.go:50-53) through one device pass above a size
threshold. The device branch (kernel + byte->G1 reconstruction without
the on-curve check) must match the host affine add bit-for-bit,
including the identity encoding.
"""

import secrets

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.models import adjust


def _same(p, q):
    return (p.inf and q.inf) or (not p.inf and not q.inf
                                 and p.x == q.x and p.y == q.y)


def _rand_pts(n):
    return [bn254.g1_mul(bn254.G1_GENERATOR, secrets.randbelow(bn254.R))
            for _ in range(n)]


class TestAdjustPoints:
    def test_device_path_parity(self):
        n = adjust._HOST_THRESHOLD + 9      # force the device branch
        pts, mns = _rand_pts(n), _rand_pts(n)
        mns[3] = pts[3]                     # difference -> identity
        mns[7] = bn254.G1_IDENTITY          # subtracting identity
        got = adjust.adjust_points(pts, mns)
        for i in range(n):
            want = bn254.g1_add(pts[i], bn254.g1_neg(mns[i]))
            assert _same(want, got[i]), i
        assert got[3].inf

    def test_host_path_parity(self):
        n = adjust._HOST_THRESHOLD - 1
        pts, mns = _rand_pts(n), _rand_pts(n)
        got = adjust.adjust_points(pts, mns)
        for i in range(n):
            assert _same(bn254.g1_add(pts[i], bn254.g1_neg(mns[i])),
                         got[i])

    def test_empty(self):
        assert adjust.adjust_points([], []) == []
