"""Finality manager escalation + FSC endorsement policy.

Mirrors reference docs/core-token.md:33-77 (delivery finality manager:
LRU cache -> listener wait -> ledger re-query -> Unknown) and
network/fabric/endorsement/approval.go + fsc_endorsement policy
(`all` | `1outn`), including MVCC rejection of stale envelopes.
"""

import threading

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.endorsement import (
    EndorsementError,
    EndorsementService,
    EndorserNode,
    LedgerQueryService,
    Policy,
)
from fabric_token_sdk_tpu.services.network.finality import (
    FinalityManager,
    FinalityStatus,
)
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus
from fabric_token_sdk_tpu.token.model import ID


@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    validator = fabtoken.new_validator(pp, Deserializer())
    ledger = MemoryLedger()
    cc = TokenChaincode(validator, ledger, pp.serialize())
    bus = SessionBus()
    issuer = TokenNode("issuer", issuer_keys, bus, cc)
    alice = TokenNode("alice", new_signing_identity(), bus, cc)
    return pp, validator, ledger, cc, bus, issuer, alice


def _issue_tx(alice):
    return alice.issue("issuer", "alice", "USD", hex(100))


# --------------------------------------------------------------- finality
def test_finality_cache_hit(net):
    _, _, ledger, cc, _, _, alice = net
    fm = FinalityManager(ledger)
    ev = alice.execute(_issue_tx(alice))
    assert ev.status == "VALID"
    # step a: straight from the LRU cache, no wait
    assert fm.is_final(ev.tx_id, timeout=0.0) == FinalityStatus.VALID


def test_finality_waits_for_future_commit(net):
    _, _, ledger, cc, _, _, alice = net
    fm = FinalityManager(ledger, listener_timeout=5.0)
    tx = _issue_tx(alice)
    results = []
    t = threading.Thread(
        target=lambda: results.append(fm.is_final(tx.tx_id)))
    t.start()
    alice.execute(tx)  # commit while the waiter is parked (step b)
    t.join(timeout=5)
    assert results == [FinalityStatus.VALID]


def test_finality_ledger_requery_after_eviction(net):
    _, _, ledger, cc, _, _, alice = net
    # tiny cache: the first tx is evicted by the ones after it
    fm = FinalityManager(ledger, lru_size=1, lru_buffer=0,
                         listener_timeout=0.0)
    first = alice.execute(_issue_tx(alice))
    for _ in range(3):
        alice.execute(_issue_tx(alice))
    assert first.tx_id not in fm._cache
    # step c: found by ledger re-query
    assert fm.is_final(first.tx_id, timeout=0.0) == FinalityStatus.VALID


def test_finality_unknown(net):
    _, _, ledger, *_ = net
    fm = FinalityManager(ledger, listener_timeout=0.0)
    assert fm.is_final("never-committed", timeout=0.0) == \
        FinalityStatus.UNKNOWN


def test_finality_listener_fires_immediately_for_past_tx(net):
    _, _, ledger, cc, _, _, alice = net
    fm = FinalityManager(ledger)
    ev = alice.execute(_issue_tx(alice))
    got = []
    fm.add_finality_listener(ev.tx_id, got.append)
    assert [e.tx_id for e in got] == [ev.tx_id]


def test_finality_listener_for_evicted_tx_fires_via_ledger_query(net):
    _, _, ledger, cc, _, _, alice = net
    fm = FinalityManager(ledger, lru_size=1, lru_buffer=0)
    first = alice.execute(_issue_tx(alice))
    for _ in range(3):
        alice.execute(_issue_tx(alice))
    assert first.tx_id not in fm._cache
    got = []
    fm.add_finality_listener(first.tx_id, got.append)
    assert [e.tx_id for e in got] == [first.tx_id]
    # and the one-shot registration did not leak
    assert not fm._listeners.get(first.tx_id)


def test_invalid_tx_status_in_cache(net):
    _, _, ledger, cc, _, _, alice = net
    fm = FinalityManager(ledger)
    ev = cc.process_request("bad-tx", b"\x00garbage")
    assert ev.status == "INVALID"
    assert fm.is_final("bad-tx", timeout=0.0) == FinalityStatus.INVALID


# ------------------------------------------------------------ endorsement
def _endorsement_net(net, policy, n_endorsers=2):
    pp, validator, ledger, cc, bus, issuer, alice = net
    names, idents = [], {}
    for i in range(n_endorsers):
        keys = new_signing_identity()
        name = f"endorser{i}"
        EndorserNode(name, keys, validator, ledger, bus)
        names.append(name)
        idents[name] = bytes(keys.identity)
    svc = EndorsementService(ledger, names, bus, idents, policy=policy)
    return svc, alice


@pytest.mark.parametrize("policy", [Policy.ALL, Policy.ONE_OUT_N])
def test_endorsed_issue_commits(net, policy):
    svc, alice = _endorsement_net(net, policy)
    tx = _issue_tx(alice)
    # sign + audit via the normal choreography, then endorse + broadcast
    from fabric_token_sdk_tpu.services.ttx import collect_endorsements

    collect_endorsements(tx, alice.bus, None)
    env = svc.request_approval(tx.tx_id, tx.request.to_bytes())
    expected = len(svc.endorser_names) if policy == Policy.ALL else 1
    assert len(env.signatures) == expected
    ev = svc.broadcast(env)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 100


def test_endorser_rejects_invalid_request(net):
    svc, alice = _endorsement_net(net, Policy.ALL)
    with pytest.raises(EndorsementError):
        svc.request_approval("tx-bad", b"\x00garbage")


def test_stale_envelope_rejected_by_mvcc(net):
    svc, alice = _endorsement_net(net, Policy.ALL)
    from fabric_token_sdk_tpu.services.ttx import collect_endorsements

    # two transfers endorsed against the same state: issue, then race
    ev = alice.execute(_issue_tx(alice))
    assert ev.status == "VALID"
    tx1 = alice.transfer("USD", hex(40), "issuer")
    collect_endorsements(tx1, alice.bus, None)
    env1 = svc.request_approval(tx1.tx_id, tx1.request.to_bytes())
    alice.selector.unselect(tx1.tx_id)  # release locks to allow the race
    tx2 = alice.transfer("USD", hex(40), "issuer")
    collect_endorsements(tx2, alice.bus, None)
    env2 = svc.request_approval(tx2.tx_id, tx2.request.to_bytes())

    assert svc.broadcast(env1).status == "VALID"
    ev2 = svc.broadcast(env2)  # same input now spent: stale endorsement
    assert ev2.status == "INVALID"
    assert "MVCC" in ev2.message


def test_tampered_envelope_rejected(net):
    svc, alice = _endorsement_net(net, Policy.ALL)
    from fabric_token_sdk_tpu.services.ttx import collect_endorsements

    tx = _issue_tx(alice)
    collect_endorsements(tx, alice.bus, None)
    env = svc.request_approval(tx.tx_id, tx.request.to_bytes())
    victim = next(k for k, v in env.writes.items() if v)
    env.writes[victim] = b"tampered"
    ev = svc.broadcast(env)
    assert ev.status == "INVALID" and "digest" in ev.message


def test_policy_1outn_survives_endorser_failure(net):
    svc, alice = _endorsement_net(net, Policy.ONE_OUT_N)
    from fabric_token_sdk_tpu.services.ttx import collect_endorsements

    # first endorser goes down: 1outn falls through to the second
    class Down:
        def endorse(self, *a):
            raise RuntimeError("unreachable")

    svc.bus.register("endorser0", Down())
    tx = _issue_tx(alice)
    collect_endorsements(tx, alice.bus, None)
    env = svc.request_approval(tx.tx_id, tx.request.to_bytes())
    assert svc.broadcast(env).status == "VALID"


def test_query_service(net):
    svc, alice = _endorsement_net(net, Policy.ALL)
    ev = alice.execute(_issue_tx(alice))
    qs = LedgerQueryService(alice.cc.ledger)
    tok = alice.tokendb.unspent_tokens("alice")[0]
    assert qs.query_tokens([tok.id])
    assert qs.are_tokens_spent([tok.id, ID("missing", 0)]) == [False, True]
