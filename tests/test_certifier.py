"""Certifier service: interactive certification flow + dummy driver.

Mirrors reference token/services/certifier (interactive/client.go scan/
request/verify/store pipeline; dummy/driver.go pass-through) over the
in-process session bus and memory ledger.
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.certifier import (
    CertificationClient,
    CertificationError,
    CertifierService,
    DummyCertificationClient,
)
from fabric_token_sdk_tpu.services.db import memdb, sqldb
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus
from fabric_token_sdk_tpu.token.model import ID


@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    certifier_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    validator = fabtoken.new_validator(pp, Deserializer())
    cc = TokenChaincode(validator, MemoryLedger(), pp.serialize())
    bus = SessionBus()
    nodes = {
        "issuer": TokenNode("issuer", issuer_keys, bus, cc,
                            auditor_name="auditor"),
        "auditor": AuditorNode("auditor", auditor_keys, bus, cc,
                               auditor_name="auditor"),
        "alice": TokenNode("alice", new_signing_identity(), bus, cc,
                           auditor_name="auditor"),
    }
    service = CertifierService("certifier", certifier_keys, cc, bus)
    return nodes, service


def _fund(nodes, amount=500):
    alice = nodes["alice"]
    ev = alice.execute(alice.issue("issuer", "alice", "USD", hex(amount)))
    assert ev.status == "VALID", ev.message


def test_scan_certifies_unspent_tokens(net):
    nodes, service = net
    _fund(nodes)
    client = CertificationClient(
        node=nodes["alice"], certifier_name="certifier",
        certifier_identity=service.identity())
    unspent = [t.id for t in nodes["alice"].tokendb.unspent_tokens("alice")]
    assert unspent and not any(client.is_certified(i) for i in unspent)

    assert client.scan() == len(unspent)
    assert all(client.is_certified(i) for i in unspent)
    # idempotent: nothing new on a second scan
    assert client.scan() == 0


def test_certification_is_a_verifiable_signature(net):
    nodes, service = net
    _fund(nodes)
    client = CertificationClient(
        node=nodes["alice"], certifier_name="certifier",
        certifier_identity=service.identity())
    client.scan()
    tok = nodes["alice"].tokendb.unspent_tokens("alice")[0]
    cert = client.db.get(tok.id)
    assert cert  # stored certification is the certifier's ECDSA signature

    # a client pinned to the WRONG certifier identity rejects the response
    rogue = CertificationClient(
        node=nodes["alice"], certifier_name="certifier",
        certifier_identity=bytes(new_signing_identity().identity))
    with pytest.raises(Exception):
        rogue.request_certification([tok.id])


def test_certify_unknown_token_fails(net):
    nodes, service = net
    client = CertificationClient(
        node=nodes["alice"], certifier_name="certifier",
        certifier_identity=service.identity(), max_attempts=2,
        wait_time=0.0)
    with pytest.raises(CertificationError):
        client.request_certification([ID("no-such-tx", 0)])


def test_dummy_driver(net):
    client = DummyCertificationClient()
    assert client.is_certified(ID("anything", 3))
    assert client.scan() == 0
    client.request_certification([ID("x", 0)])


@pytest.mark.parametrize("backend", [sqldb, memdb])
def test_certificationdb_contract(backend):
    db = backend.CertificationDB(":memory:")
    assert not db.exists(ID("t", 0))
    db.store({ID("t", 0): b"c0", ID("t", 1): b"c1"})
    assert db.exists(ID("t", 0)) and db.exists(ID("t", 1))
    assert db.get(ID("t", 0)) == b"c0"
    assert db.get(ID("t", 9)) is None
    # overwrite is last-write-wins (vault Store semantics)
    db.store({ID("t", 0): b"c0'"})
    assert db.get(ID("t", 0)) == b"c0'"
