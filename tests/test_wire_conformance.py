"""Reference-wire conformance: our encoders vs protoc-compiled protos.

The oracle modules in tests/proto_oracle/ are compiled by protoc from the
REFERENCE .proto files (token/driver/protos/request.proto, zkatdlog
noghactions.proto/noghmath.proto) — so equality here means a Go node using
the reference protobuf stack produces/accepts these exact bytes. This is
the checkable form of the SURVEY north star's "bit-identical" claim for
everything outside the proof bytes (those are pinned separately by the
crypto round-trip tests).
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent / "proto_oracle"))

import noghactions_pb2 as na  # noqa: E402
import noghmath_pb2 as nm  # noqa: E402
import noghpp_pb2 as npp  # noqa: E402
import request_pb2 as rq  # noqa: E402

from fabric_token_sdk_tpu.core.zkatdlog.actions import (ActionInput,  # noqa: E402
    IssueAction, Token, TransferAction, unmarshal_typed_token)
from fabric_token_sdk_tpu.core.zkatdlog.metadata import (  # noqa: E402
    AuditableIdentity, IssueActionMetadata, IssueOutputMetadata,
    RequestMetadata, TokenMetadata, TransferActionMetadata,
    TransferInputMetadata, TransferOutputMetadata)
from fabric_token_sdk_tpu.crypto import bn254  # noqa: E402
from fabric_token_sdk_tpu.crypto import serialization as ser  # noqa: E402
from fabric_token_sdk_tpu.driver.request import TokenRequest  # noqa: E402
from fabric_token_sdk_tpu.token.model import ID  # noqa: E402

P1 = bn254.g1_mul(bn254.G1_GENERATOR, 7)
P2 = bn254.g1_mul(bn254.G1_GENERATOR, 9)


def _oracle_token(owner=b"alice", point=P1):
    return na.Token(owner=owner, data=nm.G1(raw=ser.g1_to_bytes(point)))


def test_token_request_bytes_equal_oracle():
    ours = TokenRequest(issues=[b"issue-raw"], transfers=[b"transfer-raw"],
                        signatures=[b"s1", b"s2"],
                        auditor_signatures=[b"as"])
    oracle = rq.TokenRequest(
        version=1,
        actions=[rq.Action(type=rq.ISSUE, raw=b"issue-raw"),
                 rq.Action(type=rq.TRANSFER, raw=b"transfer-raw")],
        signatures=[rq.Signature(raw=b"s1"), rq.Signature(raw=b"s2")],
        auditor_signatures=[rq.Signature(raw=b"as")])
    assert ours.to_bytes() == oracle.SerializeToString()

    # and we parse oracle bytes identically
    parsed = TokenRequest.from_bytes(oracle.SerializeToString())
    assert parsed.issues == [b"issue-raw"]
    assert parsed.transfers == [b"transfer-raw"]
    assert parsed.signatures == [b"s1", b"s2"]
    assert parsed.auditor_signatures == [b"as"]


def test_zk_token_proto_and_typed_envelope():
    tok = Token(owner=b"alice", data=P1)
    assert tok.to_proto() == _oracle_token().SerializeToString()

    # standalone form: ASN.1 TypedToken{2, proto} (tokens/typed.go)
    wrapped = tok.serialize()
    body = unmarshal_typed_token(wrapped)
    assert body == tok.to_proto()
    assert Token.deserialize(wrapped).data == P1

    # oracle parses the embedded form
    parsed = na.Token.FromString(tok.to_proto())
    assert parsed.owner == b"alice"
    assert parsed.data.raw == ser.g1_to_bytes(P1)


def test_transfer_action_bytes_equal_oracle():
    tok_in = Token(owner=b"alice", data=P1)
    tok_out = Token(owner=b"bob", data=P2)
    ours = TransferAction(
        inputs=[ActionInput(id=ID("tx0", 3), token=tok_in)],
        outputs=[tok_out],
        proof=b"zkp",
        metadata={"k1": b"v1", "k2": b"v2"},
    )
    oracle = na.TransferAction(
        inputs=[na.TransferActionInput(
            token_id=na.TokenID(id="tx0", index=3),
            input=_oracle_token())],
        outputs=[na.TransferActionOutput(
            token=_oracle_token(b"bob", P2))],
        proof=na.Proof(proof=b"zkp"),
        metadata={"k1": b"v1", "k2": b"v2"},
    )
    assert ours.serialize() == oracle.SerializeToString(deterministic=True)

    parsed = TransferAction.deserialize(oracle.SerializeToString())
    assert parsed.inputs[0].id == ID("tx0", 3)
    assert parsed.inputs[0].token.data == P1
    assert parsed.outputs[0].owner == b"bob"
    assert parsed.proof == b"zkp"
    assert parsed.metadata == {"k1": b"v1", "k2": b"v2"}


def test_upgrade_witness_bytes_equal_oracle():
    import ftactions_pb2 as ft

    from fabric_token_sdk_tpu.core.zkatdlog.actions import UpgradeWitness

    ours = UpgradeWitness(owner=b"alice", token_type="USD",
                          quantity="0x4d", blinding_factor=777)
    oracle = na.TransferActionInputUpgradeWitness(
        output=ft.Token(owner=b"alice", type="USD", quantity="0x4d"),
        blinding_factor=nm.Zr(raw=ser.zr_to_bytes(777)))
    assert ours.serialize() == oracle.SerializeToString()
    rt = UpgradeWitness.deserialize(oracle.SerializeToString())
    assert rt.quantity == "0x4d" and rt.blinding_factor == 777


def test_issue_action_bytes_equal_oracle():
    ours = IssueAction(issuer=b"issuer-x", outputs=[Token(b"alice", P1)],
                       proof=b"zkp2")
    oracle = na.IssueAction(
        issuer=npp.Identity(raw=b"issuer-x"),
        outputs=[na.IssueActionOutput(token=_oracle_token())],
        proof=na.Proof(proof=b"zkp2"),
    )
    assert ours.serialize() == oracle.SerializeToString()
    parsed = IssueAction.deserialize(oracle.SerializeToString())
    assert bytes(parsed.issuer) == b"issuer-x"
    assert parsed.outputs[0].data == P1
    assert parsed.proof == b"zkp2"


def test_token_metadata_bytes_equal_oracle():
    ours = TokenMetadata(token_type="USD", value=1234,
                         blinding_factor=5678, issuer=b"iss")
    oracle = na.TokenMetadata(
        type="USD",
        value=nm.Zr(raw=ser.zr_to_bytes(1234)),
        blinding_factor=nm.Zr(raw=ser.zr_to_bytes(5678)),
        issuer=npp.Identity(raw=b"iss"))
    assert ours.to_proto() == oracle.SerializeToString()
    # typed envelope round trip
    assert TokenMetadata.deserialize(ours.serialize()).to_proto() == \
        ours.to_proto()


def test_request_metadata_bytes_equal_oracle():
    opening = TokenMetadata("USD", 10, 20).serialize()
    ours = RequestMetadata(
        issues=[IssueActionMetadata(
            issuer=AuditableIdentity(b"iss", b"iss-ai"),
            outputs=[IssueOutputMetadata(
                output_metadata=opening,
                receivers=[AuditableIdentity(b"alice", b"alice-ai")])])],
        transfers=[TransferActionMetadata(
            inputs=[TransferInputMetadata(
                token_id=ID("tx1", 1),
                senders=[AuditableIdentity(b"alice", b"alice-ai")])],
            outputs=[TransferOutputMetadata(
                output_metadata=opening,
                receivers=[AuditableIdentity(b"bob", b"bob-ai")])])],
    )
    oracle = rq.TokenRequestMetadata(
        version=1,
        metadata=[
            rq.ActionMetadata(issue_metadata=rq.IssueMetadata(
                issuer=rq.AuditableIdentity(
                    identity=rq.Identity(raw=b"iss"), audit_info=b"iss-ai"),
                outputs=[rq.OutputMetadata(
                    metadata=opening,
                    receivers=[rq.AuditableIdentity(
                        identity=rq.Identity(raw=b"alice"),
                        audit_info=b"alice-ai")])])),
            rq.ActionMetadata(transfer_metadata=rq.TransferMetadata(
                inputs=[rq.TransferInputMetadata(
                    token_id=rq.TokenID(tx_id="tx1", index=1),
                    senders=[rq.AuditableIdentity(
                        identity=rq.Identity(raw=b"alice"),
                        audit_info=b"alice-ai")])],
                outputs=[rq.OutputMetadata(
                    metadata=opening,
                    receivers=[rq.AuditableIdentity(
                        identity=rq.Identity(raw=b"bob"),
                        audit_info=b"bob-ai")])])),
        ])
    assert ours.serialize() == oracle.SerializeToString()

    parsed = RequestMetadata.deserialize(oracle.SerializeToString())
    assert len(parsed.issues) == 1 and len(parsed.transfers) == 1
    assert parsed.issues[0].outputs[0].output_metadata == opening
    assert parsed.transfers[0].inputs[0].token_id == ID("tx1", 1)


def test_fabtoken_typed_envelope_is_go_asn1():
    """fabtoken Output.Serialize = ASN.1 TypedToken{1, Go-json}."""
    from fabric_token_sdk_tpu.core.fabtoken.actions import Output

    out = Output(owner=b"ali", type="USD", quantity="0x64")
    raw = out.serialize()
    seq = ser.DerReader(raw).read_sequence()
    assert seq.read_integer() == 1
    body = seq.read_octet_string()
    assert body == b'{"owner":"YWxp","type":"USD","quantity":"0x64"}'
    assert Output.deserialize(raw) == out

    # omitempty: redeem output has no owner key
    redeem = Output(owner=b"", type="USD", quantity="0x1")
    body2 = unmarshal_typed = ser.DerReader(
        redeem.serialize()).read_sequence()
    body2.read_integer()
    assert b'"owner"' not in body2.read_octet_string()


def test_fabtoken_action_json_matches_go_field_names():
    from fabric_token_sdk_tpu.core.fabtoken.actions import (IssueAction,
                                                            Output,
                                                            TransferAction)

    act = TransferAction(
        inputs=[ID("t0", 0)],
        input_tokens=[Output(b"a", "USD", "0x5")],
        outputs=[Output(b"b", "USD", "0x5")])
    raw = act.serialize()
    assert raw.startswith(b'{"Inputs":[{"tx_id":"t0"}]')  # index 0 omitted
    rt = TransferAction.deserialize(raw)
    assert rt.inputs == [ID("t0", 0)]
    assert rt.input_tokens == [Output(b"a", "USD", "0x5")]

    ia = IssueAction(issuer=b"iss", outputs=[Output(b"a", "USD", "0x5")])
    assert IssueAction.deserialize(ia.serialize()).outputs == ia.outputs
