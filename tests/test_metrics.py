"""Metrics/tracing subsystem (reference metrics/provider.go:26-75,
v1/metrics.go:14-40, tracing.go:18-26)."""

import threading

from fabric_token_sdk_tpu.services.metrics import (MetricsProvider, Tracer)


def test_counter_and_histogram_with_labels():
    p = MetricsProvider({"tms": "net,ch,ns"})
    p.counter("requests_total", driver="zkatdlog").add()
    p.counter("requests_total", driver="zkatdlog").add(2)
    p.counter("requests_total", driver="fabtoken").add()
    h = p.histogram("verify_seconds")
    for v in (0.002, 0.003, 0.8):
        h.observe(v)

    snap = p.snapshot()
    zk = [(k, v) for k, v in snap.items()
          if k[0] == "requests_total" and ("driver", "zkatdlog") in k[1]]
    assert zk[0][1] == 3
    hist = [v for k, v in snap.items() if k[0] == "verify_seconds"][0]
    assert hist["count"] == 3
    assert abs(hist["sum"] - 0.805) < 1e-9


def test_with_labels_shares_registry():
    p = MetricsProvider()
    child = p.with_labels(tms="a")
    child.counter("x").add()
    assert [v for k, v in p.snapshot().items() if k[0] == "x"] == [1.0]


def test_prometheus_text_format():
    p = MetricsProvider()
    p.counter("reqs", code="200").add(5)
    p.histogram("lat").observe(0.002)
    text = p.prometheus_text()
    assert 'reqs{code="200"} 5.0' in text
    assert "lat_count " in text and "lat_sum " in text
    assert 'lat_bucket' in text


def test_histogram_thread_safety():
    p = MetricsProvider()
    h = p.histogram("hot")

    def worker():
        for _ in range(1000):
            h.observe(0.001)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.n == 8000


def test_tracer_spans_record_durations_and_events():
    p = MetricsProvider()
    tr = Tracer(provider=p)
    with tr.span("audit_check", tx_id="t1") as sp:
        sp.add_event("start_check")
        sp.add_event("end_check")
    assert tr.finished[-1].duration > 0
    assert [e[0] for e in tr.finished[-1].events] == ["start_check",
                                                      "end_check"]
    snap = p.snapshot()
    assert [v for k, v in snap.items()
            if k[0] == "span_audit_check_seconds"][0]["count"] == 1


def test_hot_path_instrumented_end_to_end():
    """The chaincode request path feeds the global registry."""
    from fabric_token_sdk_tpu.core import fabtoken
    from fabric_token_sdk_tpu.services import metrics
    from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
    from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
    from fabric_token_sdk_tpu.services.network.tcc import (MemoryLedger,
                                                           TokenChaincode)

    before = [v for k, v in metrics.GLOBAL.snapshot().items()
              if k[0] == "tcc_requests_total"]
    issuer = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer.identity]
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    cc.process_request("mtx", b"garbage")  # INVALID, still counted
    after = [v for k, v in metrics.GLOBAL.snapshot().items()
             if k[0] == "tcc_requests_total"]
    assert after and after[0] == (before[0] if before else 0) + 1


# ---------------------------------------------------------------------------
# obs subsystem: exposition conformance, span-tree export, shared-lock
# semantics, registry reset (PR: observability)
# ---------------------------------------------------------------------------

import json
import re
import time


def test_with_labels_concurrent_shared_lock():
    """Parent and label-derived children hammer the SAME series from many
    threads; the shared registry lock must make every increment stick."""
    p = MetricsProvider()
    children = [p.with_labels(node=f"n{i % 2}") for i in range(4)]

    def worker(child):
        node = child.namespace_labels["node"]
        for _ in range(2000):
            child.counter("shared_total").add()
            # same series reached through the parent with explicit labels
            p.counter("shared_total", node=node).add()

    threads = [threading.Thread(target=worker, args=(c,)) for c in children]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = p.snapshot()
    per_node = {k[1]: v for k, v in snap.items() if k[0] == "shared_total"}
    # 2 children per node label x 2000 iterations x 2 increment routes
    assert list(per_node.values()) == [8000.0, 8000.0]


def test_prometheus_exposition_conformance():
    """HELP/TYPE blocks, sanitized names (span names contain dots),
    escaped label values, +Inf bucket — the format a real Prometheus
    scraper accepts."""
    p = MetricsProvider()
    p.counter("zk.sigma verify-total", kind="type_and_sum").add(2)
    p.histogram("span_zk.verify_block_seconds").observe(0.01)
    p.counter("esc", path='C:\\dir "x"\nend').add()
    text = p.prometheus_text()

    name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? \S+$')
    typed = set()
    helped = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            helped.add(line.split(" ", 3)[2])
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert parts[3] in ("counter", "histogram")
            typed.add(parts[2])
            continue
        m = sample_re.match(line)
        assert m, f"malformed sample line: {line!r}"
        base = re.sub(r"_(bucket|sum|count)$", "", m.group(1))
        assert base in typed or m.group(1) in typed, \
            f"sample before its TYPE: {line!r}"
    assert typed == helped
    # dots/spaces/dashes sanitized out of family names
    assert "zk_sigma_verify_total" in typed
    assert "span_zk_verify_block_seconds" in typed
    # label escaping: backslash, quote, newline
    assert r'path="C:\\dir \"x\"\nend"' in text
    # histogram terminal bucket
    assert 'le="+Inf"' in text


def test_chrome_trace_round_trip_preserves_nesting():
    """Span tree -> Chrome trace-event JSON -> parse -> the tree
    reconstructs exactly from the parent_id args."""
    from fabric_token_sdk_tpu.obs import spans_to_chrome_trace

    tr = Tracer(provider=MetricsProvider())
    with tr.span("root", kind="test"):
        with tr.span("child_a") as a:
            a.add_event("marker", detail=1)
            with tr.span("leaf"):
                pass
        with tr.span("child_b"):
            pass
    doc = json.loads(json.dumps(spans_to_chrome_trace(tr.roots)))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert all(e["dur"] >= 0 for e in xs)
    children: dict = {}
    for e in xs:
        children.setdefault(e["args"]["parent_id"], []).append(e["name"])
    ids = {e["name"]: e["args"]["span_id"] for e in xs}
    assert children[None] == ["root"]
    assert sorted(children[ids["root"]]) == ["child_a", "child_b"]
    assert children[ids["child_a"]] == ["leaf"]
    # one trace id across the whole tree
    assert len({e["args"]["trace_id"] for e in xs}) == 1
    # the instant event rides its owning span's id
    inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert [e["name"] for e in inst] == ["marker"]
    assert inst[0]["args"]["span_id"] == ids["child_a"]
    # ts/dur containment: children inside the root's window
    root = next(e for e in xs if e["name"] == "root")
    for e in xs:
        assert e["ts"] >= root["ts"] - 1
        assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1


def test_tracer_nesting_via_contextvar_across_helpers():
    """Layers that never see each other's span objects still produce one
    connected tree (the node -> tcc -> validator -> batch path)."""
    tr = Tracer(provider=MetricsProvider())

    def inner_layer():
        with tr.span("inner"):
            pass

    with tr.span("outer") as outer:
        inner_layer()
    assert [c.name for c in outer.children] == ["inner"]
    assert tr.last_root("outer") is outer
    assert outer.children[0].parent_id == outer.span_id


def test_global_reset_isolates_state():
    from fabric_token_sdk_tpu.core.zkatdlog import verifier
    from fabric_token_sdk_tpu.services import metrics

    metrics.GLOBAL.counter("zk_device_oracle_disagreements_total").add(2)
    assert verifier.DEVICE_DISAGREEMENTS == 2
    metrics.GLOBAL.reset()
    assert verifier.DEVICE_DISAGREEMENTS == 0
    assert not [k for k in metrics.GLOBAL.snapshot()
                if k[0] == "zk_device_oracle_disagreements_total"]


def test_span_overhead_is_negligible():
    """Acceptance bound: tracing must stay far below the per-batch work
    it wraps. Bound is generous (500us/span) vs the observed ~2us so a
    loaded CI host cannot flake it."""
    tr = Tracer(provider=MetricsProvider(), keep_spans=8)
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        with tr.span("overhead_probe"):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 5e-4


def test_histogram_percentiles_from_reservoir():
    p = MetricsProvider()
    h = p.histogram("lat")
    for i in range(1, 101):
        h.observe(i / 1000.0)
    assert abs(h.percentile(50) - 0.050) <= 0.002
    assert abs(h.percentile(99) - 0.099) <= 0.002


def test_empty_histogram_percentile_and_exposition():
    """A registered-but-never-observed histogram must neither raise on
    percentile() nor emit malformed exposition lines (PR: telemetry
    satellite — /statusz and bench reports read percentiles off live
    registries that may contain cold instruments)."""
    p = MetricsProvider()
    h = p.histogram("cold_seconds")
    assert h.percentile(50) == 0.0
    assert h.percentile(99) == 0.0
    assert h.mean == 0.0
    text = p.prometheus_text()
    assert 'cold_seconds_bucket{le="+Inf"} 0' in text
    assert "cold_seconds_sum 0.0" in text
    assert "cold_seconds_count 0" in text


def test_help_text_escaping_differs_from_label_values():
    """HELP lines are unquoted: only backslash and line feed get escaped,
    double quotes pass through verbatim. Label values escape all three."""
    from fabric_token_sdk_tpu.obs import escape_help_text, escape_label_value

    tricky = 'path "C:\\tmp"\nsecond line'
    assert escape_help_text(tricky) == 'path "C:\\\\tmp"\\nsecond line'
    assert escape_label_value(tricky) == \
        'path \\"C:\\\\tmp\\"\\nsecond line'

    p = MetricsProvider()
    p.counter("weird_total", help=tricky).add()
    text = p.prometheus_text()
    help_line = next(l for l in text.splitlines()
                     if l.startswith("# HELP weird_total"))
    assert help_line == \
        '# HELP weird_total path "C:\\\\tmp"\\nsecond line'
    assert "\n\n" not in text  # the newline never splits the HELP line


def test_nonfinite_sample_values_render_conformantly():
    """Prometheus exposition spells non-finite values NaN/+Inf/-Inf;
    Python's repr ("inf", "nan") would poison the whole scrape."""
    p = MetricsProvider()
    p.gauge("g_inf").set(float("inf"))
    p.gauge("g_ninf").set(float("-inf"))
    p.gauge("g_nan").set(float("nan"))
    p.counter("c_inf").add(float("inf"))
    h = p.histogram("h_inf")
    h.observe(float("inf"))
    text = p.prometheus_text()
    assert "g_inf +Inf" in text
    assert "g_ninf -Inf" in text
    assert "g_nan NaN" in text
    assert "c_inf +Inf" in text
    assert "h_inf_sum +Inf" in text
    # +Inf observation lands in the overflow bucket, count stays exact
    assert 'h_inf_bucket{le="+Inf"} 1' in text
    for token in ("inf", "nan"):
        assert f" {token}" not in text, \
            f"raw Python float repr {token!r} leaked into the exposition"


def test_bench_snapshot_rolls_up_registry():
    from fabric_token_sdk_tpu.obs import bench_snapshot
    from fabric_token_sdk_tpu.obs.pipeline import (BatchRecord,
                                                   PipelineRecorder)

    p = MetricsProvider()
    rec = PipelineRecorder(provider=p)
    cold = rec.is_cold("range_verify", (16, 256))
    assert cold and not rec.is_cold("range_verify", (16, 256))
    rec.record(BatchRecord(kind="range_verify", batch=100, live=90,
                           bucket=128, padded_rows=128, total_s=0.5,
                           host_prep_s=0.2, device_execute_s=0.25,
                           result_fetch_s=0.05, path="combined",
                           cold_compile=True))
    rec.record(BatchRecord(kind="range_verify", batch=100, live=100,
                           bucket=128, padded_rows=128, total_s=0.1,
                           path="combined"))
    snap = bench_snapshot(provider=p, recorder=rec)
    assert snap["pipeline"]["batches"] == 2
    assert snap["pipeline"]["cold_compiles"] == 1
    # steady-state stats exclude the cold batch
    assert snap["pipeline"]["steady"]["batches"] == 1
    assert snap["pipeline"]["steady"]["p50_s"] == 0.1
    states = {d["labels"]["state"]
              for d in snap["counters"]["pipeline_batches_total"]}
    assert states == {"cold", "steady"}
    hist = snap["histograms"]["pipeline_steady_seconds"][0]
    assert hist["count"] == 1 and hist["p50"] == 0.1
