"""Metrics/tracing subsystem (reference metrics/provider.go:26-75,
v1/metrics.go:14-40, tracing.go:18-26)."""

import threading

from fabric_token_sdk_tpu.services.metrics import (MetricsProvider, Tracer)


def test_counter_and_histogram_with_labels():
    p = MetricsProvider({"tms": "net,ch,ns"})
    p.counter("requests_total", driver="zkatdlog").add()
    p.counter("requests_total", driver="zkatdlog").add(2)
    p.counter("requests_total", driver="fabtoken").add()
    h = p.histogram("verify_seconds")
    for v in (0.002, 0.003, 0.8):
        h.observe(v)

    snap = p.snapshot()
    zk = [(k, v) for k, v in snap.items()
          if k[0] == "requests_total" and ("driver", "zkatdlog") in k[1]]
    assert zk[0][1] == 3
    hist = [v for k, v in snap.items() if k[0] == "verify_seconds"][0]
    assert hist["count"] == 3
    assert abs(hist["sum"] - 0.805) < 1e-9


def test_with_labels_shares_registry():
    p = MetricsProvider()
    child = p.with_labels(tms="a")
    child.counter("x").add()
    assert [v for k, v in p.snapshot().items() if k[0] == "x"] == [1.0]


def test_prometheus_text_format():
    p = MetricsProvider()
    p.counter("reqs", code="200").add(5)
    p.histogram("lat").observe(0.002)
    text = p.prometheus_text()
    assert 'reqs{code="200"} 5.0' in text
    assert "lat_count " in text and "lat_sum " in text
    assert 'lat_bucket' in text


def test_histogram_thread_safety():
    p = MetricsProvider()
    h = p.histogram("hot")

    def worker():
        for _ in range(1000):
            h.observe(0.001)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.n == 8000


def test_tracer_spans_record_durations_and_events():
    p = MetricsProvider()
    tr = Tracer(provider=p)
    with tr.span("audit_check", tx_id="t1") as sp:
        sp.add_event("start_check")
        sp.add_event("end_check")
    assert tr.finished[-1].duration > 0
    assert [e[0] for e in tr.finished[-1].events] == ["start_check",
                                                      "end_check"]
    snap = p.snapshot()
    assert [v for k, v in snap.items()
            if k[0] == "span_audit_check_seconds"][0]["count"] == 1


def test_hot_path_instrumented_end_to_end():
    """The chaincode request path feeds the global registry."""
    from fabric_token_sdk_tpu.core import fabtoken
    from fabric_token_sdk_tpu.services import metrics
    from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
    from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
    from fabric_token_sdk_tpu.services.network.tcc import (MemoryLedger,
                                                           TokenChaincode)

    before = [v for k, v in metrics.GLOBAL.snapshot().items()
              if k[0] == "tcc_requests_total"]
    issuer = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer.identity]
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    cc.process_request("mtx", b"garbage")  # INVALID, still counted
    after = [v for k, v in metrics.GLOBAL.snapshot().items()
             if k[0] == "tcc_requests_total"]
    assert after and after[0] == (before[0] if before else 0) + 1
