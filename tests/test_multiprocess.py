"""NWO-style multiprocess e2e: real OS processes over a shared ledger
process (reference integration/nwo/token platform + fungible TestAll
shape, SURVEY.md §4 'multi-node without real cluster')."""

import pytest

from fabric_token_sdk_tpu.harness import NodeSpec, Platform


@pytest.fixture
def platform():
    p = Platform(specs=[
        NodeSpec("issuer", role="issuer"),
        NodeSpec("auditor", role="auditor"),
        NodeSpec("alice"),
        NodeSpec("bob"),
    ])
    p.start()
    yield p
    p.stop()


def test_multiprocess_issue_transfer_redeem(platform):
    p = platform
    tx1 = p.issue(via="alice", issuer="issuer", to="alice",
                  token_type="USD", amount=1000)
    assert p.wait_tx("alice", tx1) == "Confirmed"
    assert p.balance("alice", "USD") == 1000

    tx2 = p.transfer(via="alice", token_type="USD", amount=300, to="bob")
    assert p.wait_tx("alice", tx2) == "Confirmed"
    # bob's delivery service ingests asynchronously; wait on his balance
    import time

    deadline = time.time() + 10
    while time.time() < deadline and p.balance("bob", "USD") != 300:
        time.sleep(0.05)
    assert p.balance("bob", "USD") == 300
    assert p.balance("alice", "USD") == 700

    tx3 = p.transfer(via="bob", token_type="USD", amount=100, to="",
                     redeem=True)
    assert p.wait_tx("bob", tx3) == "Confirmed"
    assert p.balance("bob", "USD") == 200


def test_platform_boots_from_tokengen_artifacts(tmp_path):
    """tokengen artifacts gen -> Platform.from_artifacts: the CLI's
    topology artifacts drive the NWO harness exactly like the reference's
    artifactgen + nwo pairing (cmd/tokengen/main.go:50)."""
    import json

    from fabric_token_sdk_tpu.cmd.tokengen import main

    topo = {"driver": "fabtoken", "precision": 64,
            "nodes": [{"name": "issuer", "role": "issuer"},
                      {"name": "auditor", "role": "auditor"},
                      {"name": "alice"}, {"name": "bob"}]}
    tf = tmp_path / "topology.json"
    tf.write_text(json.dumps(topo))
    out = tmp_path / "artifacts"
    assert main(["artifacts", "gen", "--topology", str(tf),
                 "--output", str(out)]) == 0

    p = Platform.from_artifacts(out)
    p.start()
    try:
        tx = p.issue(via="alice", issuer="issuer", to="alice",
                     token_type="USD", amount=42)
        assert p.wait_tx("alice", tx) == "Confirmed"
        assert p.balance("alice", "USD") == 42
    finally:
        p.stop()


def test_fleet_federation_across_node_processes(tmp_path):
    """Fleet observability over real OS processes: every node process
    publishes its own metrics registry into the platform spool
    (obs/aggregate.py) and stamps lifecycle heartbeats; the parent's
    federated exposition is grammar-valid, carries one ``node`` label per
    process, and keeps the stable family names untouched."""
    from test_telemetry import validate_prometheus

    from fabric_token_sdk_tpu.obs.heartbeat import read_last

    spool = tmp_path / "spool"
    names = ("issuer", "auditor", "alice", "bob")
    p = Platform(specs=[
        NodeSpec("issuer", role="issuer"),
        NodeSpec("auditor", role="auditor"),
        NodeSpec("alice"),
        NodeSpec("bob"),
    ], fleet_spool_dir=str(spool))
    p.start()
    try:
        tx = p.issue(via="alice", issuer="issuer", to="alice",
                     token_type="USD", amount=5)
        assert p.wait_tx("alice", tx) == "Confirmed"
        assert p.balance("alice", "USD") == 5
    finally:
        p.stop()   # each node's publisher does a final flush on stop

    text = p.fleet_aggregator().collect()
    types = validate_prometheus(text)
    for n in names:
        assert f'node="{n}"' in text, f"no federated samples from {n}"
    # node registries merged under their own (stable) family names —
    # federation adds a dimension, it never renames a family
    assert "ttx_executions_total" in types
    assert "fleet_nodes" in types and "fleet_node_age_seconds" in types
    # lifecycle heartbeats rode along in the same spool
    stamp = read_last(spool / "alice.hb.jsonl")
    assert stamp is not None and stamp["phase"] == "stopped"


@pytest.mark.slow
@pytest.mark.crash
def test_supervised_restart_mid_load_reconstructs_balances(tmp_path):
    """Crash-recovery acceptance: SIGKILL one node mid-load under the
    resilience supervisor. The replacement must come back as the same
    logical party (persisted signing key under ``state_dir``), replay
    the ledger from cursor 0, and reconstruct balances — while the rest
    of the topology keeps transacting."""
    import os
    import signal
    import time

    spool = tmp_path / "spool"
    state = tmp_path / "state"
    p = Platform(specs=[
        NodeSpec("issuer", role="issuer"),
        NodeSpec("alice"),
        NodeSpec("bob"),
    ], fleet_spool_dir=str(spool), state_dir=str(state), supervise=True)
    p.start()
    try:
        tx = p.issue(via="alice", issuer="issuer", to="alice",
                     token_type="USD", amount=1000)
        assert p.wait_tx("alice", tx) == "Confirmed"
        tx2 = p.transfer(via="alice", token_type="USD", amount=300,
                         to="bob")
        assert p.wait_tx("alice", tx2) == "Confirmed"

        pid = p._procs["bob"].pid
        os.kill(pid, signal.SIGKILL)
        deadline = time.time() + 60
        while time.time() < deadline:
            proc = p._procs["bob"]
            if proc.pid != pid and proc.is_alive():
                break
            time.sleep(0.1)
        assert p._procs["bob"].pid != pid, "supervisor never respawned bob"

        # more load while the replacement replays the ledger
        tx3 = p.transfer(via="alice", token_type="USD", amount=200,
                         to="bob")
        assert p.wait_tx("alice", tx3) == "Confirmed"

        deadline = time.time() + 30
        while time.time() < deadline and p.balance("bob", "USD") != 500:
            time.sleep(0.1)
        assert p.balance("bob", "USD") == 500   # both transfers survived
        assert p.balance("alice", "USD") == 500

        from fabric_token_sdk_tpu.obs import GLOBAL
        failures = sum(
            v for (name, labels), v in GLOBAL.snapshot().items()
            if name == "crash_failures_total"
            and dict(labels).get("child") == "bob")
        assert failures >= 1
    finally:
        p.stop(raise_on_error=False)


@pytest.mark.slow
@pytest.mark.crash
def test_stop_surfaces_nonzero_exit_codes():
    """Platform.stop must report a node that crashed on its own instead
    of silently reaping it (and must not blame its own terminate/kill
    escalation on the node)."""
    p = Platform(specs=[NodeSpec("issuer", role="issuer"),
                        NodeSpec("alice")])
    p.start()
    p._procs["alice"].kill()
    p._procs["alice"].join(timeout=10)
    with pytest.raises(RuntimeError, match="alice"):
        p.stop()


def test_multiprocess_double_spend_rejected(platform):
    p = platform
    tx1 = p.issue(via="alice", issuer="issuer", to="alice",
                  token_type="EUR", amount=10)
    p.wait_tx("alice", tx1)
    tx2 = p.transfer(via="alice", token_type="EUR", amount=10, to="bob")
    p.wait_tx("alice", tx2)
    # alice's tokens are spent; further spend must fail (selector finds
    # nothing — the insufficient-funds guard on a live multiprocess net)
    with pytest.raises(RuntimeError):
        p.transfer(via="alice", token_type="EUR", amount=10, to="bob")
