"""Concurrency/race coverage (reference `unit-tests-race` target,
Makefile:42-45: the Go race detector is their only sanitizer; here the
equivalent is hammering the shared structures from threads and asserting
the invariants that the race detector would protect).
"""

import threading

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.db import memdb, sqldb
from fabric_token_sdk_tpu.services.db.sqldb import DBError
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.selector import InsufficientFunds
from fabric_token_sdk_tpu.services.ttx import SessionBus, TtxError
from fabric_token_sdk_tpu.token.model import ID


def _run_threads(n, target):
    threads = [threading.Thread(target=target, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


@pytest.mark.parametrize("backend", [sqldb, memdb])
def test_eid_lock_race_single_winner(backend):
    """auditor EID locking: concurrent audits of the same enrollment id —
    exactly one transaction may hold the lock (auditdb lock semantics)."""
    a = backend.AuditDB(":memory:")
    wins = []

    def worker(i):
        try:
            a.acquire_locks(f"tx{i}", ["hot-eid"])
            wins.append(i)
        except DBError:
            pass

    _run_threads(16, worker)
    assert len(wins) == 1


@pytest.mark.parametrize("backend", [sqldb, memdb])
def test_tokendb_concurrent_store_and_read(backend):
    t = backend.TokenDB(":memory:")
    errors = []

    def worker(i):
        try:
            for j in range(20):
                t.store_token(ID(f"tx{i}", j), b"o", "USD", "0x1", ["w"])
                t.balance("w", "USD")
                t.unspent_tokens("w")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    _run_threads(8, worker)
    assert not errors
    assert t.balance("w", "USD") == 8 * 20


def test_ledger_mvcc_serializes_double_spend():
    """Two RW sets reading the same key race to commit: MVCC admits only
    the first (translator double-spend semantics under concurrency)."""
    ledger = MemoryLedger()
    ledger.state["k"] = b"v0"
    results = []
    all_read = threading.Barrier(8)

    def worker(i):
        rws = ledger.new_rwset()
        assert rws.get_state("k") == b"v0"
        rws.delete_state("k")
        all_read.wait()  # every tx reads the SAME snapshot, then they race
        results.append(ledger.commit(f"tx{i}", rws).status)

    _run_threads(8, worker)
    assert sorted(results)[-1] == "VALID"
    assert results.count("VALID") == 1
    assert results.count("INVALID") == 7


def test_concurrent_transfers_conserve_balance():
    """Race many transfers out of one wallet: the sherdlock selector +
    token locks must prevent double-spends; total conservation holds."""
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    bus = SessionBus()
    TokenNode("issuer", issuer_keys, bus, cc, auditor_name="auditor")
    AuditorNode("auditor", auditor_keys, bus, cc, auditor_name="auditor")
    alice = TokenNode("alice", new_signing_identity(), bus, cc,
                      auditor_name="auditor")
    bob = TokenNode("bob", new_signing_identity(), bus, cc,
                    auditor_name="auditor")
    # 10 separate 10-unit tokens
    for _ in range(10):
        assert alice.execute(
            alice.issue("issuer", "alice", "USD", hex(10))).status == "VALID"

    outcomes = []

    def worker(i):
        try:
            tx = alice.transfer("USD", hex(10), "bob")
            outcomes.append(alice.execute(tx).status)
        except (InsufficientFunds, TtxError, DBError) as e:
            outcomes.append(type(e).__name__)

    _run_threads(12, worker)  # more spenders than tokens
    valid = outcomes.count("VALID")
    assert valid <= 10
    assert alice.balance("USD") + bob.balance("USD") == 100
    assert bob.balance("USD") == valid * 10


def test_session_bus_concurrent_registration():
    bus = SessionBus()

    def worker(i):
        bus.register(f"n{i}", object())
        for j in range(i + 1):
            try:
                bus.node(f"n{j}")
            except TtxError:
                pass  # not registered yet by its thread

    _run_threads(16, worker)
    assert len(bus.nodes) == 16
