"""Tier-1 wrapper around scripts/check_trace_parent.py: every
serve-side span created while handling an RPC frame (``rpc.serve`` /
``rpc.serve_batch`` in serve/rpc.py and serve/worker.py, and the
trace_ctx-driven ``serve.request`` in serve/service.py) must join the
caller's trace via ``remote_parent=``.

A handler that drops the kwarg does not fail any behavioral test — the
frame still serves — it just forks a disconnected trace, which only
shows up when someone stares at a broken /tracez during an incident.
This test makes the propagation contract part of the suite.
"""

import importlib.util
import pathlib

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
           / "check_trace_parent.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_trace_parent",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_frame_handler_spans_join_the_callers_trace():
    mod = _load()
    offenders = mod.find_offenders()
    assert not offenders, (
        "serve-side frame-handler spans must pass remote_parent=ctx "
        f"(extracted wire context) so traces join across the hop: "
        f"{offenders}")


def test_linter_sees_the_handler_span_sites():
    """Guard the guard: the scan must actually find the rpc.serve and
    serve.request creation sites, or a rename would turn the lint into
    a silent no-op."""
    import ast
    mod = _load()
    names = set()
    for fname in ("rpc.py", "worker.py", "service.py"):
        tree = ast.parse((mod.SERVE / fname).read_text())
        names.update(n for n, _, _ in mod._span_calls(tree))
    assert "rpc.serve" in names
    assert "rpc.serve_batch" in names
    assert "serve.request" in names
