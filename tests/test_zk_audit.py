"""zkatdlog auditor unit tests: batched commitment re-open + identity match.

Mirror of reference crypto/audit/auditor_test.go: valid issue/transfer
requests pass Check; a wrong opening, wrong audit info, or mismatched
metadata count fails with the reference's first-failure ordering.
"""

import pytest

from fabric_token_sdk_tpu.core.zkatdlog.actions import (ActionInput,
                                                        IssueAction, Token,
                                                        TransferAction)
from fabric_token_sdk_tpu.core.zkatdlog.audit import AuditError, Auditor
from fabric_token_sdk_tpu.core.zkatdlog.metadata import (
    AuditableIdentity, IssueActionMetadata, IssueOutputMetadata,
    RequestMetadata, TokenMetadata, TransferActionMetadata,
    TransferInputMetadata, TransferOutputMetadata)
from fabric_token_sdk_tpu.crypto import setup, token_commit
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.token.model import ID

BIT_LENGTH = 16

ISSUER = b"issuer-identity"
ALICE = b"alice-identity"
BOB = b"bob-identity"


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT_LENGTH)


@pytest.fixture(scope="module")
def auditor(pp):
    return Auditor(pp, device=True)


def _issue_with_md(pp, values, owner=ALICE):
    coms, wits = token_commit.get_tokens_with_witness(
        values, "USD", pp.pedersen_generators)
    action = IssueAction(
        issuer=ISSUER,
        outputs=[Token(owner=owner, data=c) for c in coms],
        proof=b"p",
    )
    md = IssueActionMetadata(
        issuer=AuditableIdentity(identity=ISSUER, audit_info=ISSUER),
        outputs=[IssueOutputMetadata(
            output_metadata=TokenMetadata(
                token_type=w.token_type, value=w.value,
                blinding_factor=w.blinding_factor,
                issuer=ISSUER).serialize(),
            receivers=[AuditableIdentity(identity=owner, audit_info=owner)])
            for w in wits],
    )
    return action, md, coms, wits


def test_issue_check_passes(pp, auditor):
    action, md, _, _ = _issue_with_md(pp, [10, 20, 30])
    req = TokenRequest(issues=[action.serialize()])
    auditor.check(req, RequestMetadata(issues=[md]), [], "tx1")


def test_issue_wrong_opening_rejected(pp, auditor):
    action, md, _, wits = _issue_with_md(pp, [10, 20])
    bad = TokenMetadata(token_type="USD", value=wits[1].value + 1,
                        blinding_factor=wits[1].blinding_factor,
                        issuer=ISSUER)
    md.outputs[1].output_metadata = bad.serialize()
    req = TokenRequest(issues=[action.serialize()])
    with pytest.raises(AuditError, match=r"output at index \[1\]"):
        auditor.check(req, RequestMetadata(issues=[md]), [], "tx2")


def test_issue_wrong_type_rejected(pp, auditor):
    action, md, _, wits = _issue_with_md(pp, [10])
    bad = TokenMetadata(token_type="EUR", value=wits[0].value,
                        blinding_factor=wits[0].blinding_factor)
    md.outputs[0].output_metadata = bad.serialize()
    req = TokenRequest(issues=[action.serialize()])
    with pytest.raises(AuditError, match=r"output at index \[0\]"):
        auditor.check(req, RequestMetadata(issues=[md]), [], "tx3")


def test_issue_wrong_audit_info_rejected(pp, auditor):
    action, md, _, _ = _issue_with_md(pp, [10])
    md.outputs[0].receivers[0].audit_info = BOB  # owner is ALICE
    req = TokenRequest(issues=[action.serialize()])
    with pytest.raises(AuditError, match="does not match"):
        auditor.check(req, RequestMetadata(issues=[md]), [], "tx4")


def test_metadata_count_mismatch(pp, auditor):
    action, md, _, _ = _issue_with_md(pp, [10])
    req = TokenRequest(issues=[action.serialize()])
    with pytest.raises(AuditError, match="number of issues"):
        auditor.check(req, RequestMetadata(issues=[]), [], "tx5")


def _transfer_with_md(pp, in_values, out_values):
    in_coms, in_wits = token_commit.get_tokens_with_witness(
        in_values, "USD", pp.pedersen_generators)
    out_coms, out_wits = token_commit.get_tokens_with_witness(
        out_values, "USD", pp.pedersen_generators)
    in_tokens = [Token(owner=ALICE, data=c) for c in in_coms]
    action = TransferAction(
        inputs=[ActionInput(id=ID("prev", i), token=t)
                for i, t in enumerate(in_tokens)],
        outputs=[Token(owner=BOB, data=c) for c in out_coms],
        proof=b"p",
    )
    md = TransferActionMetadata(
        inputs=[TransferInputMetadata(
            token_id=ID("prev", i),
            senders=[AuditableIdentity(identity=ALICE, audit_info=ALICE)])
            for i in range(len(in_tokens))],
        outputs=[TransferOutputMetadata(
            output_metadata=TokenMetadata(
                token_type=w.token_type, value=w.value,
                blinding_factor=w.blinding_factor).serialize(),
            receivers=[AuditableIdentity(identity=BOB, audit_info=BOB)])
            for w in out_wits],
    )
    return action, md, in_tokens


def test_transfer_check_passes(pp, auditor):
    action, md, in_tokens = _transfer_with_md(pp, [30], [10, 20])
    req = TokenRequest(transfers=[action.serialize()])
    auditor.check(req, RequestMetadata(transfers=[md]), [in_tokens], "tx6")


def test_transfer_wrong_opening_rejected(pp, auditor):
    action, md, in_tokens = _transfer_with_md(pp, [30], [10, 20])
    opening = TokenMetadata.deserialize(md.outputs[0].output_metadata)
    opening.blinding_factor += 1
    md.outputs[0].output_metadata = opening.serialize()
    req = TokenRequest(transfers=[action.serialize()])
    with pytest.raises(AuditError, match=r"transfer in tx \[tx7\]"):
        auditor.check(req, RequestMetadata(transfers=[md]), [in_tokens],
                      "tx7")


def test_transfer_sender_audit_info_mismatch(pp, auditor):
    action, md, in_tokens = _transfer_with_md(pp, [30], [30])
    md.inputs[0].senders[0].audit_info = BOB  # sender is ALICE
    req = TokenRequest(transfers=[action.serialize()])
    with pytest.raises(AuditError, match="does not match"):
        auditor.check(req, RequestMetadata(transfers=[md]), [in_tokens],
                      "tx8")


def test_mixed_request_one_device_batch(pp, auditor):
    """Issues + transfers re-opened in one batched device pass."""
    i_action, i_md, _, _ = _issue_with_md(pp, [5, 6, 7])
    t_action, t_md, in_tokens = _transfer_with_md(pp, [18], [9, 9])
    req = TokenRequest(issues=[i_action.serialize()],
                       transfers=[t_action.serialize()])
    md = RequestMetadata(issues=[i_md], transfers=[t_md])
    auditor.check(req, md, [in_tokens], "tx9")


def test_endorse_requires_signer(pp, auditor):
    req = TokenRequest()
    with pytest.raises(AuditError, match="signer is nil"):
        auditor.endorse(req, "tx10")
