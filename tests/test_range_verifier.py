"""Batched TPU range verifier vs host oracle: exact accept/reject parity."""

import random

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier

rng = random.Random(0xBA7C4)

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT_LENGTH)


def _prove_one(pp, value):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    bf = bn254.fr_rand()
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length)
    return proof, com


def _oracle_ok(pp, proof, com):
    rpp = pp.range_proof_params
    try:
        rp.range_verify(proof, com, pp.pedersen_generators[1:3],
                        rpp.left_generators, rpp.right_generators,
                        rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        return True
    except rp.ProofError:
        return False


def test_batch_accepts_valid_and_rejects_tampered(pp):
    proofs, coms = [], []
    for v in [0, 1, 7, (1 << BIT_LENGTH) - 1, rng.randrange(1 << BIT_LENGTH)]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)

    # Tampered variants: each mutates one transcript-relevant component.
    t0, c0 = _prove_one(pp, 99)
    t0.data.tau = bn254.fr_add(t0.data.tau, 1)
    proofs.append(t0); coms.append(c0)

    t1, c1 = _prove_one(pp, 100)
    t1.data.T1 = bn254.g1_add(t1.data.T1, bn254.G1_GENERATOR)
    proofs.append(t1); coms.append(c1)

    t2, c2 = _prove_one(pp, 101)
    t2.ipa.left = bn254.fr_add(t2.ipa.left, 1)
    proofs.append(t2); coms.append(c2)

    t3, c3 = _prove_one(pp, 102)
    t3.ipa.L[0] = bn254.g1_add(t3.ipa.L[0], bn254.G1_GENERATOR)
    proofs.append(t3); coms.append(c3)

    t4, c4 = _prove_one(pp, 103)
    t4.data.delta = bn254.fr_add(t4.data.delta, 1)
    proofs.append(t4); coms.append(c4)

    # Wrong commitment (proof valid, statement false).
    t5, _ = _prove_one(pp, 104)
    _, cwrong = _prove_one(pp, 105)
    proofs.append(t5); coms.append(cwrong)

    # Structurally broken proof (nil element).
    t6, c6 = _prove_one(pp, 106)
    t6.data.T1 = None
    proofs.append(t6); coms.append(c6)

    got = BatchRangeVerifier(pp).verify(proofs, coms)
    want = np.array([_oracle_ok(pp, pf, cm) for pf, cm in zip(proofs, coms)])
    assert want[:5].all() and not want[5:].any()  # sanity on the oracle
    assert (got == want).all(), f"device {got} != oracle {want}"


def test_batch_roundtrip_through_serialization(pp):
    """Proofs that crossed the wire verify identically."""
    proofs, coms = [], []
    for v in [3, 250]:
        pf, com = _prove_one(pp, v)
        raw = pf.serialize()
        restored = rp.RangeProof.deserialize(raw)
        assert restored.serialize() == raw
        proofs.append(restored)
        coms.append(com)
    got = BatchRangeVerifier(pp).verify(proofs, coms)
    assert got.all()


def test_verify_emits_span_tree_and_batch_record(pp):
    """Acceptance for the observability PR: one verify() call must leave
    an exportable span tree with the host_prep / device_execute /
    result_fetch phase children plus a pipeline BatchRecord."""
    from fabric_token_sdk_tpu.obs import RECORDS, TRACER, \
        spans_to_chrome_trace

    proofs, coms = [], []
    for v in [2, 9, 31]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    TRACER.clear()
    RECORDS.reset()
    assert BatchRangeVerifier(pp).verify(proofs, coms).all()

    root = TRACER.last_root("range_verify")
    assert root is not None and root.duration > 0
    phases = {c.name for c in root.children}
    assert {"host_prep", "device_execute", "result_fetch"} <= phases
    # phase durations nest inside the root wall time
    assert sum(c.duration for c in root.children) <= root.duration * 1.05
    # exportable: Chrome trace events for the whole tree
    events = spans_to_chrome_trace(TRACER.roots)["traceEvents"]
    assert {e["name"] for e in events if e["ph"] == "X"} >= phases

    rec = RECORDS.last("range_verify")
    assert rec is not None
    assert rec.live == 3 and rec.batch == 3
    assert rec.padded_rows >= rec.bucket >= rec.live
    assert 0.0 <= rec.pad_waste < 1.0
    assert rec.cold_compile  # fresh recorder: first sighting of the shape
    assert rec.total_s > 0 and rec.host_prep_s >= 0
    s = RECORDS.summary()
    assert s["batches"] == 1 and s["cold_compiles"] == 1


# ---------------------------------------------------------------------------
# fixed-base table cache (FTS_TABLE_CACHE_DIR)
# ---------------------------------------------------------------------------

def test_table_cache_roundtrip(tmp_path, monkeypatch):
    """uint8 .npz round-trip is bit-exact in both directions and inert
    when the env opt-in is absent or the digest/flavor differs."""
    import jax.numpy as jnp

    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.ops import ec

    monkeypatch.setenv("FTS_TABLE_CACHE_DIR", str(tmp_path))
    raw = np.random.default_rng(3).integers(
        0, 256, size=(2, 32, 4, 96), dtype=np.uint8)
    planes = jnp.asarray(raw).astype(ec.plane_dtype())
    rv._table_cache_save(16, "cafef00d", "proj", planes)
    assert list(tmp_path.glob("fbtables_n16_cafef00d_proj.npz"))
    got = rv._table_cache_load(16, "cafef00d", "proj")
    assert got is not None and got.dtype == ec.plane_dtype()
    assert (np.asarray(got.astype(jnp.float32)).astype(np.uint8)
            == raw).all()
    # misses: wrong flavor, wrong digest, empty digest
    assert rv._table_cache_load(16, "cafef00d", "affine") is None
    assert rv._table_cache_load(16, "0badd00d", "proj") is None
    assert rv._table_cache_load(16, "", "proj") is None
    # corrupt file degrades to a rebuild, not a crash
    f = next(tmp_path.glob("*.npz"))
    f.write_bytes(b"not an npz")
    assert rv._table_cache_load(16, "cafef00d", "proj") is None
    # opt-in absent -> loader and saver are inert
    monkeypatch.delenv("FTS_TABLE_CACHE_DIR")
    rv._table_cache_save(16, "cafef00d", "proj", planes)
    assert rv._table_cache_load(16, "cafef00d", "proj") is None


def test_from_pp_serves_tables_from_cache(pp, monkeypatch):
    """A cache hit must skip the device table build entirely (the >= 2x
    repeat-run warm-up win) and wire the cached planes straight into the
    params object."""
    from fabric_token_sdk_tpu.models import range_verifier as rv

    real = rv._params_for(pp).tables  # built once by the module fixture
    seen = []

    def fake_load(n, digest, flavor):
        seen.append((n, digest, flavor))
        return real

    def boom(*_a, **_k):
        raise AssertionError("table kernel ran despite a cache hit")

    monkeypatch.setattr(rv, "_table_cache_load", fake_load)
    monkeypatch.setattr(rv, "_tables_kernel", boom)
    monkeypatch.setattr(rv, "_raw_tables_kernel", boom)
    params = rv.RangeVerifierParams.from_pp(pp, cache_digest="cachetest")
    assert params.tables is real
    assert seen and seen[0] == (BIT_LENGTH, "cachetest", "proj")
