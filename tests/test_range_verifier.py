"""Batched TPU range verifier vs host oracle: exact accept/reject parity."""

import random

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier

rng = random.Random(0xBA7C4)

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT_LENGTH)


def _prove_one(pp, value):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    bf = bn254.fr_rand()
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length)
    return proof, com


def _oracle_ok(pp, proof, com):
    rpp = pp.range_proof_params
    try:
        rp.range_verify(proof, com, pp.pedersen_generators[1:3],
                        rpp.left_generators, rpp.right_generators,
                        rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        return True
    except rp.ProofError:
        return False


def test_batch_accepts_valid_and_rejects_tampered(pp):
    proofs, coms = [], []
    for v in [0, 1, 7, (1 << BIT_LENGTH) - 1, rng.randrange(1 << BIT_LENGTH)]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)

    # Tampered variants: each mutates one transcript-relevant component.
    t0, c0 = _prove_one(pp, 99)
    t0.data.tau = bn254.fr_add(t0.data.tau, 1)
    proofs.append(t0); coms.append(c0)

    t1, c1 = _prove_one(pp, 100)
    t1.data.T1 = bn254.g1_add(t1.data.T1, bn254.G1_GENERATOR)
    proofs.append(t1); coms.append(c1)

    t2, c2 = _prove_one(pp, 101)
    t2.ipa.left = bn254.fr_add(t2.ipa.left, 1)
    proofs.append(t2); coms.append(c2)

    t3, c3 = _prove_one(pp, 102)
    t3.ipa.L[0] = bn254.g1_add(t3.ipa.L[0], bn254.G1_GENERATOR)
    proofs.append(t3); coms.append(c3)

    t4, c4 = _prove_one(pp, 103)
    t4.data.delta = bn254.fr_add(t4.data.delta, 1)
    proofs.append(t4); coms.append(c4)

    # Wrong commitment (proof valid, statement false).
    t5, _ = _prove_one(pp, 104)
    _, cwrong = _prove_one(pp, 105)
    proofs.append(t5); coms.append(cwrong)

    # Structurally broken proof (nil element).
    t6, c6 = _prove_one(pp, 106)
    t6.data.T1 = None
    proofs.append(t6); coms.append(c6)

    got = BatchRangeVerifier(pp).verify(proofs, coms)
    want = np.array([_oracle_ok(pp, pf, cm) for pf, cm in zip(proofs, coms)])
    assert want[:5].all() and not want[5:].any()  # sanity on the oracle
    assert (got == want).all(), f"device {got} != oracle {want}"


def test_batch_roundtrip_through_serialization(pp):
    """Proofs that crossed the wire verify identically."""
    proofs, coms = [], []
    for v in [3, 250]:
        pf, com = _prove_one(pp, v)
        raw = pf.serialize()
        restored = rp.RangeProof.deserialize(raw)
        assert restored.serialize() == raw
        proofs.append(restored)
        coms.append(com)
    got = BatchRangeVerifier(pp).verify(proofs, coms)
    assert got.all()


def test_verify_emits_span_tree_and_batch_record(pp):
    """Acceptance for the observability PR: one verify() call must leave
    an exportable span tree with the host_prep / device_execute /
    result_fetch phase children plus a pipeline BatchRecord."""
    from fabric_token_sdk_tpu.obs import RECORDS, TRACER, \
        spans_to_chrome_trace

    proofs, coms = [], []
    for v in [2, 9, 31]:
        pf, com = _prove_one(pp, v)
        proofs.append(pf)
        coms.append(com)
    TRACER.clear()
    RECORDS.reset()
    assert BatchRangeVerifier(pp).verify(proofs, coms).all()

    root = TRACER.last_root("range_verify")
    assert root is not None and root.duration > 0
    phases = {c.name for c in root.children}
    assert {"host_prep", "device_execute", "result_fetch"} <= phases
    # phase durations nest inside the root wall time
    assert sum(c.duration for c in root.children) <= root.duration * 1.05
    # exportable: Chrome trace events for the whole tree
    events = spans_to_chrome_trace(TRACER.roots)["traceEvents"]
    assert {e["name"] for e in events if e["ph"] == "X"} >= phases

    rec = RECORDS.last("range_verify")
    assert rec is not None
    assert rec.live == 3 and rec.batch == 3
    assert rec.padded_rows >= rec.bucket >= rec.live
    assert 0.0 <= rec.pad_waste < 1.0
    assert rec.cold_compile  # fresh recorder: first sighting of the shape
    assert rec.total_s > 0 and rec.host_prep_s >= 0
    s = RECORDS.summary()
    assert s["batches"] == 1 and s["cold_compiles"] == 1


# ---------------------------------------------------------------------------
# fixed-base table cache (FTS_TABLE_CACHE_DIR)
# ---------------------------------------------------------------------------

def test_table_cache_roundtrip(tmp_path, monkeypatch):
    """uint8 .npz round-trip is bit-exact in both directions and inert
    when the env opt-in is absent or the digest/flavor differs."""
    import jax.numpy as jnp

    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.ops import ec

    monkeypatch.setenv("FTS_TABLE_CACHE_DIR", str(tmp_path))
    raw = np.random.default_rng(3).integers(
        0, 256, size=(2, 32, 4, 96), dtype=np.uint8)
    planes = jnp.asarray(raw).astype(ec.plane_dtype())
    rv._table_cache_save(16, "cafef00d", "proj", planes)
    assert list(tmp_path.glob("fbtables_n16_cafef00d_proj.npz"))
    got = rv._table_cache_load(16, "cafef00d", "proj")
    assert got is not None and got.dtype == ec.plane_dtype()
    assert (np.asarray(got.astype(jnp.float32)).astype(np.uint8)
            == raw).all()
    # misses: wrong flavor, wrong digest, empty digest
    assert rv._table_cache_load(16, "cafef00d", "affine") is None
    assert rv._table_cache_load(16, "0badd00d", "proj") is None
    assert rv._table_cache_load(16, "", "proj") is None
    # corrupt file degrades to a rebuild, not a crash
    f = next(tmp_path.glob("*.npz"))
    f.write_bytes(b"not an npz")
    assert rv._table_cache_load(16, "cafef00d", "proj") is None
    # opt-in absent -> loader and saver are inert
    monkeypatch.delenv("FTS_TABLE_CACHE_DIR")
    rv._table_cache_save(16, "cafef00d", "proj", planes)
    assert rv._table_cache_load(16, "cafef00d", "proj") is None


def test_from_pp_serves_tables_from_cache(pp, monkeypatch):
    """A cache hit must skip the device table build entirely (the >= 2x
    repeat-run warm-up win) and wire the cached planes straight into the
    params object."""
    from fabric_token_sdk_tpu.models import range_verifier as rv

    real = rv._params_for(pp).tables  # built once by the module fixture
    seen = []

    def fake_load(n, digest, flavor):
        seen.append((n, digest, flavor))
        return real

    def boom(*_a, **_k):
        raise AssertionError("table kernel ran despite a cache hit")

    monkeypatch.setattr(rv, "_table_cache_load", fake_load)
    monkeypatch.setattr(rv, "_tables_kernel", boom)
    monkeypatch.setattr(rv, "_raw_tables_kernel", boom)
    params = rv.RangeVerifierParams.from_pp(pp, cache_digest="cachetest")
    assert params.tables is real
    assert seen and seen[0] == (BIT_LENGTH, "cachetest", "proj")


# ---------------------------------------------------------------------------
# round-7 fused chunk pipeline: 1 packed upload + 1 device program per chunk
# ---------------------------------------------------------------------------

def _hook_counts(monkeypatch):
    """Install a dispatch-count recorder on the verifier's seam."""
    import collections

    from fabric_token_sdk_tpu.models import range_verifier as rv

    counts = collections.Counter()
    monkeypatch.setattr(rv, "_DISPATCH_HOOK",
                        lambda kind: counts.update((kind,)))
    return rv, counts


def test_fused_pipeline_single_dispatch_per_chunk(pp, monkeypatch):
    """The round-7 acceptance gate: on the single-host hot path a chunk
    costs exactly ONE packed host->device upload and ONE fused device
    program (pass-1 + round digests + derived var scalars + the pass-2
    combined-RLC partial), with only the cross-chunk finalize left as a
    separate dispatch."""
    rv, counts = _hook_counts(monkeypatch)
    proofs, coms = zip(*[_prove_one(pp, v) for v in (5, 17, 650)])
    verifier = BatchRangeVerifier(pp)
    assert verifier.mesh is None and rv._fused_pipeline_enabled()
    assert verifier.verify(list(proofs), list(coms)).all()
    assert verifier.last_path == "combined"
    assert counts["chunk_upload"] == 1, counts
    assert counts["chunk_dispatch"] == 1, counts
    assert counts["finalize"] == 1, counts


def test_fused_pipeline_multi_chunk(pp, monkeypatch):
    """Chunked batches scale the invariant linearly: N chunks -> N
    uploads + N dispatches, still one finalize (same 16-row bucket as
    the single-chunk test, so no extra compile)."""
    rv, counts = _hook_counts(monkeypatch)
    monkeypatch.setattr(rv, "_CHUNK_ROWS", 2)
    proofs, coms = zip(*[_prove_one(pp, v) for v in (1, 2, 3, 4)])
    assert BatchRangeVerifier(pp).verify(list(proofs), list(coms)).all()
    assert counts["chunk_upload"] == 2, counts
    assert counts["chunk_dispatch"] == 2, counts
    assert counts["finalize"] == 1, counts


def test_split_pipeline_escape_matches_verdicts(pp, monkeypatch):
    """FTS_NO_FUSED_PIPELINE keeps the legacy split pass-1/pass-2 path
    alive (the mesh / debug escape): verdicts identical, but the chunk
    costs multiple uploads + dispatches again."""
    rv, counts = _hook_counts(monkeypatch)
    monkeypatch.setenv("FTS_NO_FUSED_PIPELINE", "1")
    assert not rv._fused_pipeline_enabled()
    good, gcom = _prove_one(pp, 7)
    bad, bcom = _prove_one(pp, 9)
    bad.data.tau = bn254.fr_add(bad.data.tau, 1)
    got = BatchRangeVerifier(pp).verify([good, bad], [gcom, bcom])
    assert got[0] and not got[1]
    assert counts["chunk_upload"] > 1 or counts["chunk_dispatch"] > 1


def test_kernel_cost_fused_exposes_pass12_on_cpu(pp):
    """kernel_cost_fused must lower the merged chunk program and report
    it under the pass12_fused kind on EVERY backend (the CPU flavor runs
    the same program structure with XLA kernel bodies) — this is what
    prewarm publishes on the stable profile_* families."""
    costs = BatchRangeVerifier(pp).kernel_cost_fused(3)
    assert costs is not None and "pass12_fused" in costs
    assert costs["pass12_fused"].get("flops", 0) > 0


def test_derive_var_scalars_matches_host(pp):
    """On-device var-scalar derivation (the enabler for folding pass-2
    into pass-1) is bit-identical to host Fr arithmetic for all seven
    scalar kinds — including the round challenges recovered from the
    device-computed digests and their Fermat inverses — and maps the
    all-zero pad row to all-zero scalars."""
    import jax.numpy as jnp

    from fabric_token_sdk_tpu.crypto.bn254 import fr_mul, fr_sub
    from fabric_token_sdk_tpu.models import range_verifier as rv
    from fabric_token_sdk_tpu.ops import limbs

    R = bn254.R
    B, rr = 3, 4
    vals = {}
    sc4 = np.zeros((B, 4, 16), dtype=np.uint32)
    w12 = np.zeros((B, 2, 16), dtype=np.uint32)
    for b in range(B):
        yinv, z, delta, x = [rng.randrange(R) for _ in range(4)]
        w1, w2 = 1 + rng.randrange(R - 1), 1 + rng.randrange(R - 1)
        vals[b] = (z, x, w1, w2)
        for j, v in enumerate((yinv, z, delta, x)):
            sc4[b, j] = limbs.int_to_limbs(v)
        w12[b, 0] = limbs.int_to_limbs(w1)
        w12[b, 1] = limbs.int_to_limbs(w2)
    rdig = np.random.default_rng(11).integers(
        0, 1 << 32, size=(B, rr, 8), dtype=np.uint32)
    sc4[B - 1] = 0          # pad-row convention: all-zero row in,
    w12[B - 1] = 0          # all-zero scalars out (identity no-ops)
    vals[B - 1] = (0, 0, 0, 0)

    got = np.asarray(rv._derive_var_scalars(
        jnp.asarray(sc4), jnp.asarray(w12), jnp.asarray(rdig), rr))
    assert got.shape == (B, 2 + 2 * rr + 3, 16)
    for b in range(B):
        z, x, w1, w2 = vals[b]
        xrs = [int.from_bytes(
            b"".join(int(w).to_bytes(4, "big") for w in rdig[b, r]),
            "big") % R for r in range(rr)]
        xinvs = [pow(xr, R - 2, R) for xr in xrs]
        want = [fr_mul(w2, fr_sub(0, x)), fr_mul(w2, R - 1)]
        want += [fr_mul(w2, fr_sub(0, fr_mul(xr, xr))) for xr in xrs]
        want += [fr_mul(w2, fr_sub(0, fr_mul(xi, xi))) for xi in xinvs]
        want += [fr_mul(w1, fr_sub(0, x)),
                 fr_mul(w1, fr_sub(0, fr_mul(x, x))),
                 fr_mul(w1, fr_sub(0, fr_mul(z, z)))]
        if b == B - 1:
            assert all(v == 0 for v in want)   # sanity on the reference
        for t in range(2 + 2 * rr + 3):
            g = limbs.limbs_to_int(got[b, t])
            assert g == want[t], (b, t, hex(g), hex(want[t]))
