"""Tier-1 wrapper around scripts/check_socket_timeouts.py: every
blocking socket/pipe wait in the serving plane (serve/, resilience/,
obs/telemetry.py, obs/aggregate.py) must carry an explicit timeout,
run under an asyncio ``wait_for``, or carry a documented
``# io-deadline:`` waiver naming what bounds it from outside.

A hung read with no deadline is how rc=124-with-no-diagnosis comes
back; this test makes the invariant part of the suite so a new
unbounded wait fails CI, not just the linter nobody ran.
"""

import ast
import importlib.util
import pathlib
import textwrap

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
           / "check_socket_timeouts.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_socket_timeouts",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _offenders_in(mod, source: str):
    """Run the lint walker over an inline snippet."""
    tree = ast.parse(textwrap.dedent(source))
    waived = {i + 1 for i, line in
              enumerate(textwrap.dedent(source).splitlines())
              if mod.WAIVER in line}
    walker = mod._Walker(waived)
    walker.visit(tree)
    return walker.offenders


def test_serving_plane_has_no_unbounded_waits():
    mod = _load()
    offenders = mod.find_offenders()
    assert not offenders, (
        "unbounded blocking waits in the serving plane (add a timeout, "
        "wrap in wait_for(), or document the outer bound with "
        f"'# io-deadline: <why>'): {offenders}")


def test_linter_sees_the_scope():
    """Guard the guard: the lint must actually be walking the serving
    plane, or a path regression turns it into a silent no-op."""
    mod = _load()
    files = mod._scope_files()
    names = {f.name for f in files}
    assert {"rpc.py", "rpc_client.py", "worker.py", "supervisor.py",
            "telemetry.py", "aggregate.py", "columnar.py"} <= names
    assert len(files) > 8


def test_detects_unbounded_sync_wait():
    mod = _load()
    bad = _offenders_in(mod, """
        def f(conn):
            conn.poll()
            conn.recv()
    """)
    assert {name for _, name, _ in bad} == {"poll", "recv"}


def test_timeouts_and_waivers_satisfy_the_lint():
    mod = _load()
    ok = _offenders_in(mod, """
        async def f(conn, reader, ev):
            conn.poll(5.0)
            conn.wait(timeout=1.0)
            await ev.wait()
            await wait_for(reader.readexactly(12), 5.0)
            data = conn.recv(4096)  # io-deadline: settimeout tick
    """)
    assert ok == []


def test_detects_unbounded_zero_copy_reads():
    """The columnar batch read path fills preallocated buffers with
    recv_into/readinto — those block exactly like recv and must be
    surfaced by the lint, not slip past it as 'has an argument'."""
    mod = _load()
    assert {"recv_into", "readinto"} <= mod.READ_WAITS
    bad = _offenders_in(mod, """
        def f(sock, view, raw):
            sock.recv_into(view)
            raw.readinto(view)
    """)
    assert {name for _, name, _ in bad} == {"recv_into", "readinto"}


def test_waiver_bounds_the_batch_decode_read():
    """The server's zero-copy frame read (serve/rpc.py recv_exact_sock)
    rides a settimeout tick; the same waiver idiom must satisfy the
    lint for recv_into as it does for recv."""
    mod = _load()
    ok = _offenders_in(mod, """
        def f(sock, view):
            k = sock.recv_into(view)  # io-deadline: settimeout tick
    """)
    assert ok == []
