"""Tier-1 CPU smoke for the serve/ frontend against a REAL verifier:
boot with tiny buckets, drive ~32 concurrent asyncio requests, and
assert (a) prewarm populated every configured bucket BEFORE the first
dispatch, (b) the demuxed verdicts are bit-identical to the direct
batched call — single-request batch, max-batch, and mixed
accept/reject — and (c) the stable ``serve_*`` metric family is
emitted. Buckets (4, 8) pad to the shared 16-row device bucket, so the
compiled kernels are the same persistent-cache entries the other heavy
tests use; ONE module-scoped ZKVerifier pays the table build once."""

import asyncio
import random

import pytest

from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.serve import (LANE_BULK, LANE_INTERACTIVE,
                                        STATUS_OK, ServeConfig,
                                        VerificationService)

rng = random.Random(0x5E47E)

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def pp():
    return setup.setup(BIT_LENGTH)


@pytest.fixture(scope="module")
def zk(pp):
    return ZKVerifier(pp, device=True)


def _prove_one(pp, value):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    bf = bn254.fr_rand()
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length)
    return proof, com


# Batch EXECUTION alone can exceed the production 2 s default deadline on a
# slow CPU host; the smoke validates prewarm/demux correctness, not SLO
# timing, so give requests a deadline no sane run can miss.
_SMOKE_DEADLINE_S = 900.0


def test_serve_smoke_concurrent_requests(pp, zk):
    cfg = ServeConfig(buckets=(4, 8), max_wait_s=0.005,
                      default_deadline_s=_SMOKE_DEADLINE_S)
    svc = VerificationService(zk, config=cfg)
    pairs = [_prove_one(pp, rng.randrange(1 << BIT_LENGTH))
             for _ in range(4)]

    async def run():
        prewarm_s = await svc.start()
        # every configured bucket compiled before anything dispatched
        assert svc.prewarm.ready == set(cfg.buckets)
        assert svc.first_dispatch_t is None
        assert prewarm_s > 0.0
        results = await asyncio.gather(*[
            svc.submit_range(
                *pairs[i % len(pairs)],
                lane=LANE_INTERACTIVE if i % 2 else LANE_BULK)
            for i in range(32)])
        await svc.stop()
        return results

    results = asyncio.run(run())
    assert len(results) == 32
    assert all(r.ok and r.accepted for r in results)
    assert svc.first_dispatch_t is not None
    # every request rode a batch bounded by the configured ladder
    assert all(1 <= r.batch_rows <= cfg.max_batch for r in results)

    # the stable serve_* family (ROADMAP bench interface) is emitted
    text = GLOBAL.prometheus_text()
    for fam in ("serve_requests_total", "serve_queue_depth",
                "serve_batches_total", "serve_batch_fill_ratio",
                "serve_batch_rows", "serve_wait_seconds",
                "serve_dispatch_seconds", "serve_prewarm_seconds",
                "serve_results_total"):
        assert fam in text, f"missing serve family: {fam}"


def test_serve_verdicts_bit_identical_to_direct(pp, zk):
    proofs, coms = [], []
    for i in range(8):
        pf, com = _prove_one(pp, rng.randrange(1 << BIT_LENGTH))
        if i in (1, 4, 6):  # mixed accept/reject demux
            pf.data.tau = bn254.fr_add(pf.data.tau, 1)
        proofs.append(pf)
        coms.append(com)

    direct_single = zk._range.verify([proofs[0]], [coms[0]])
    direct_full = zk._range.verify(proofs, coms)

    cfg = ServeConfig(buckets=(8,), max_wait_s=0.01,
                      default_deadline_s=_SMOKE_DEADLINE_S)
    svc = VerificationService(zk, config=cfg)

    async def run():
        await svc.start(prewarm=False)  # kernels already warm (same zk)
        # single-request path: one request alone -> a 1-row batch
        single = await svc.submit_range(proofs[0], coms[0])
        # max-batch path: 8 concurrent submits fill bucket 8
        full = await asyncio.gather(*[
            svc.submit_range(p, c) for p, c in zip(proofs, coms)])
        await svc.stop()
        return single, full

    single, full = asyncio.run(run())
    assert single.status == STATUS_OK
    assert single.accepted == bool(direct_single[0])
    assert all(r.status == STATUS_OK for r in full)
    assert [r.accepted for r in full] == [bool(x) for x in direct_full]


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_chaos_real_device_parity(pp, zk):
    """Real-device chaos smoke: scripted transient faults on the device
    entry point, then a forced-open breaker. Both phases must return
    verdicts bit-identical to the direct device call — the first served
    by the device after retries, the second by the pure-host fallback."""
    from fabric_token_sdk_tpu.resilience import FaultInjector, \
        ResilienceConfig
    from fabric_token_sdk_tpu.serve import SERVED_BY_HOST

    proofs, coms = [], []
    for i in range(4):
        pf, com = _prove_one(pp, rng.randrange(1 << BIT_LENGTH))
        if i == 2:  # one forged proof: parity covers rejects too
            pf.data.tau = bn254.fr_add(pf.data.tau, 1)
        proofs.append(pf)
        coms.append(com)
    direct = [bool(x) for x in zk._range.verify(proofs, coms)]

    inj = FaultInjector(seed=0, schedule={0: "transient", 1: "transient"})
    svc = VerificationService(
        inj.wrap(zk),
        config=ServeConfig(buckets=(4,), max_wait_s=0.01,
                           default_deadline_s=_SMOKE_DEADLINE_S),
        resilience=ResilienceConfig(retry_attempts=4, retry_base_s=0.0,
                                    retry_cap_s=0.0,
                                    watchdog_timeout_s=None))

    async def run():
        await svc.start(prewarm=False)  # kernels already warm (same zk)
        faulted = await asyncio.gather(*[
            svc.submit_range(p, c) for p, c in zip(proofs, coms)])
        svc._breaker.force_open()
        hosted = await asyncio.gather(*[
            svc.submit_range(p, c) for p, c in zip(proofs, coms)])
        await svc.stop(timeout_s=120.0)
        return faulted, hosted

    faulted, hosted = asyncio.run(run())
    assert inj.injected["transient"] == 2
    assert all(r.status == STATUS_OK for r in faulted + hosted)
    assert [r.accepted for r in faulted] == direct, \
        "device-path verdicts diverge under injected transient faults"
    assert [r.accepted for r in hosted] == direct, \
        "host-fallback verdicts diverge from the device path"
    assert all(r.served_by == SERVED_BY_HOST for r in hosted)
