"""EC kernel vs pure-Python oracle: Jacobian add/double/scalar-mul/MSM."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import ec, limbs

rng = random.Random(0xEC)


def _rand_point():
    return bn254.g1_mul(bn254.G1_GENERATOR, rng.randrange(1, bn254.R))


def _to_dev(points):
    return jnp.asarray(limbs.points_to_projective_limbs(points))


def _from_dev(arr):
    arr = np.asarray(arr)
    if arr.ndim == 2:
        return limbs.projective_limbs_to_point(arr)
    return [limbs.projective_limbs_to_point(a) for a in arr]


def test_double_matches_oracle():
    pts = [_rand_point() for _ in range(6)] + [bn254.G1_IDENTITY]
    out = _from_dev(jax.jit(ec.double)(_to_dev(pts)))
    for p, got in zip(pts, out):
        assert got == bn254.g1_double(p)


def test_add_all_edge_cases():
    p = _rand_point()
    q = _rand_point()
    cases = [
        (p, q),                        # generic
        (p, p),                        # doubling via add
        (p, bn254.g1_neg(p)),          # annihilation -> identity
        (bn254.G1_IDENTITY, q),        # left identity
        (p, bn254.G1_IDENTITY),        # right identity
        (bn254.G1_IDENTITY, bn254.G1_IDENTITY),
    ]
    lhs = _to_dev([c[0] for c in cases])
    rhs = _to_dev([c[1] for c in cases])
    out = _from_dev(jax.jit(ec.add)(lhs, rhs))
    for (a, b), got in zip(cases, out):
        assert got == bn254.g1_add(a, b)


def test_neg_and_equal():
    p = _rand_point()
    dev = _to_dev([p, bn254.G1_IDENTITY])
    negd = _from_dev(jax.jit(ec.neg)(dev))
    assert negd[0] == bn254.g1_neg(p)
    assert negd[1] == bn254.G1_IDENTITY
    # points_equal across different Z representations: compare P+Q (jacobian
    # accumulation) against the affine upload of the oracle's sum.
    q = _rand_point()
    summed = jax.jit(ec.add)(_to_dev([p]), _to_dev([q]))
    expect = _to_dev([bn254.g1_add(p, q)])
    eqfn = jax.jit(ec.points_equal)
    assert bool(np.asarray(eqfn(summed, expect))[0])
    assert not bool(np.asarray(eqfn(summed, _to_dev([p])))[0])


def test_scalar_mul():
    pts = [_rand_point() for _ in range(3)] + [bn254.G1_IDENTITY]
    scalars = [rng.randrange(bn254.R) for _ in range(2)] + [0, 5]
    fn = jax.jit(ec.scalar_mul)
    out = _from_dev(fn(_to_dev(pts), jnp.asarray(limbs.scalars_to_limbs(scalars))))
    for p, s, got in zip(pts, scalars, out):
        assert got == bn254.g1_mul(p, s)


def test_msm_matches_oracle():
    B, T = 3, 5
    pts = [[_rand_point() for _ in range(T)] for _ in range(B)]
    scalars = [[rng.randrange(bn254.R) for _ in range(T)] for _ in range(B)]
    dev_pts = jnp.stack([_to_dev(row) for row in pts])
    dev_sc = jnp.stack([jnp.asarray(limbs.scalars_to_limbs(row)) for row in scalars])
    out = np.asarray(jax.jit(ec.msm)(dev_pts, dev_sc))
    for b in range(B):
        expect = bn254.msm(pts[b], scalars[b])
        assert limbs.projective_limbs_to_point(out[b]) == expect


def test_msm_is_identity():
    # Construct sum_t s_t P_t == O by balancing: s0*P + s1*P - (s0+s1)*P.
    p = _rand_point()
    s0, s1 = rng.randrange(bn254.R), rng.randrange(bn254.R)
    good_pts = [p, p, p]
    good_sc = [s0, s1, bn254.R - (s0 + s1) % bn254.R]
    bad_sc = [s0, s1, bn254.R - (s0 + s1 + 1) % bn254.R]
    dev_pts = jnp.stack([_to_dev(good_pts), _to_dev(good_pts)])
    dev_sc = jnp.stack([
        jnp.asarray(limbs.scalars_to_limbs(good_sc)),
        jnp.asarray(limbs.scalars_to_limbs(bad_sc)),
    ])
    res = np.asarray(jax.jit(ec.msm_is_identity)(dev_pts, dev_sc))
    assert list(res) == [True, False]
