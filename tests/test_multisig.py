"""Multisig escrow: co-owned tokens requiring all co-signatures (reference
token/services/identity/multisig + ttx/multisig)."""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.multisig import (
    MultiIdentity, MultisigError, MultiSignature, MultisigVerifier,
    join_signatures, unwrap, wrap_identities)
from fabric_token_sdk_tpu.services.identity.x509 import (X509Verifier,
                                                         new_signing_identity)
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus


# ------------------------------------------------------------------- unit

def test_multi_identity_roundtrip_and_unwrap():
    a, b = b"alice-id", b"bob-id"
    owner = wrap_identities(a, b)
    is_ms, ids = unwrap(bytes(owner))
    assert is_ms and ids == [a, b]
    # non-multisig identities unwrap as (False, [])
    assert unwrap(b"plain")[0] is False
    mi = MultiIdentity([a, b])
    assert MultiIdentity.deserialize(mi.serialize()).identities == [a, b]


def test_multisig_verifier_requires_all_signatures():
    k1, k2 = new_signing_identity(), new_signing_identity()
    msg = b"spend escrow token"
    verifier = MultisigVerifier([X509Verifier(k1.private_key.public_key()),
                                 X509Verifier(k2.private_key.public_key())])
    ids = [bytes(k1.identity), bytes(k2.identity)]
    good = join_signatures(ids, {ids[0]: k1.sign(msg), ids[1]: k2.sign(msg)})
    verifier.verify(msg, good)

    # one signature swapped for garbage -> reject with index
    bad = MultiSignature([k1.sign(msg), b"garbage"]).serialize()
    with pytest.raises(MultisigError, match=r"index \[1\]"):
        verifier.verify(msg, bad)

    # wrong count
    short = MultiSignature([k1.sign(msg)]).serialize()
    with pytest.raises(MultisigError, match="expect"):
        verifier.verify(msg, short)

    # signatures in the WRONG order must fail (order is identity order)
    swapped = MultiSignature([k2.sign(msg), k1.sign(msg)]).serialize()
    with pytest.raises(MultisigError):
        verifier.verify(msg, swapped)


def test_join_signatures_missing_co_owner():
    with pytest.raises(MultisigError, match="missing"):
        join_signatures([b"a", b"b"], {b"a": b"s"})


# -------------------------------------------------------------------- e2e

@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    bus = SessionBus()
    nodes = {
        "issuer": TokenNode("issuer", issuer_keys, bus, cc,
                            auditor_name="auditor"),
        "auditor": AuditorNode("auditor", auditor_keys, bus, cc,
                               auditor_name="auditor"),
    }
    for n in ("alice", "bob", "charlie"):
        nodes[n] = TokenNode(n, new_signing_identity(), bus, cc,
                             auditor_name="auditor")
    return nodes


def test_escrow_lock_and_cosigned_spend(net):
    alice, bob, charlie = net["alice"], net["bob"], net["charlie"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(100))).status == "VALID"

    # lock 60 into escrow co-owned by alice+bob
    tx = alice.lock_in_escrow("USD", hex(60), ["alice", "bob"])
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 40  # change only
    assert alice.tokendb.balance("alice.ms", "USD") == 60
    assert bob.tokendb.balance("bob.ms", "USD") == 60

    # both co-owners sign -> spend to charlie succeeds
    tx2 = alice.spend_escrow("USD", "charlie", ["alice", "bob"])
    ev = alice.execute(tx2)
    assert ev.status == "VALID", ev.message
    assert charlie.balance("USD") == 60
    assert alice.tokendb.balance("alice.ms", "USD") == 0


def test_escrow_spend_without_co_owner_rejected(net):
    alice, bob = net["alice"], net["bob"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(50))).status == "VALID"
    tx = alice.lock_in_escrow("USD", hex(50), ["alice", "bob"])
    assert alice.execute(tx).status == "VALID"

    # alice alone tries to spend: selection fails fast — no escrow token is
    # fully signable by the listed co-owners (ttx/multisig wallet filter)
    with pytest.raises(Exception):
        alice.spend_escrow("USD", "alice", ["alice"])
    # escrow funds untouched
    assert alice.tokendb.balance("alice.ms", "USD") == 50


def test_escrow_partner_sets_do_not_mix(net):
    """alice holds escrows with DIFFERENT partner sets; spending with one
    set must only select that set's tokens."""
    alice, bob, charlie = net["alice"], net["bob"], net["charlie"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(100))).status == "VALID"
    assert alice.execute(
        alice.lock_in_escrow("USD", hex(40), ["alice", "bob"])
    ).status == "VALID"
    assert alice.execute(
        alice.lock_in_escrow("USD", hex(25), ["alice", "charlie"])
    ).status == "VALID"
    assert alice.tokendb.balance("alice.ms", "USD") == 65

    tx = alice.spend_escrow("USD", "bob", ["alice", "bob"])
    assert alice.execute(tx).status == "VALID"
    # only the alice+bob escrow moved; the alice+charlie one remains
    assert net["bob"].balance("USD") == 40
    assert alice.tokendb.balance("alice.ms", "USD") == 25


def test_escrow_wrong_cosigner_rejected(net):
    """charlie (not a co-owner) cannot stand in for bob."""
    alice, charlie = net["alice"], net["charlie"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(30))).status == "VALID"
    tx = alice.lock_in_escrow("USD", hex(30), ["alice", "bob"])
    assert alice.execute(tx).status == "VALID"
    # charlie cannot cover bob's component: selection refuses the spend
    with pytest.raises(Exception):
        alice.spend_escrow("USD", "alice", ["alice", "charlie"])
