"""Shared DB contract suite run against EVERY backend (reference
token/services/db/dbtest: same suite, many drivers).

Backends: sqlite, memory, and the postgres dialect (pgdb). Without a
postgres server/driver in the environment, pgdb runs over the fake DB-API
driver (tests/fakepg.py) that validates the emitted postgres SQL on
sqlite's matching ON CONFLICT machinery; set PG_DSN (with psycopg2
installed) to run the same suite against a live server — the reference's
testcontainers pattern."""

import functools
import os
import threading
import time
import types

import pytest

import fakepg
from fabric_token_sdk_tpu.services.db import memdb, pgdb, sqldb
from fabric_token_sdk_tpu.services.db.sqldb import DBError, TxRecord, TxStatus
from fabric_token_sdk_tpu.token.model import ID

_STORES = ("TokenDB", "TransactionDB", "AuditDB", "TokenLockDB",
           "IdentityDB", "CertificationDB")


def _pg_store(store_cls, dsn, driver_module, _path=None):
    # the contract suite passes a sqlite-style path; the pg dialect keys
    # off its DSN instead
    return store_cls(dsn, driver_module=driver_module)


def _pg_backend(driver_module, dsn: str):
    ns = types.SimpleNamespace()
    for store in _STORES:
        setattr(ns, store,
                functools.partial(_pg_store, getattr(pgdb, store), dsn,
                                  driver_module))
    return ns


BACKENDS = {
    "sqlite": sqldb,
    "memory": memdb,
    "postgres-dialect": _pg_backend(fakepg, ":fake:"),
}
if pgdb.available() and os.environ.get("PG_DSN"):
    import psycopg2

    BACKENDS["postgres"] = _pg_backend(psycopg2, os.environ["PG_DSN"])


@pytest.fixture(params=sorted(BACKENDS))
def db(request):
    return BACKENDS[request.param]


def test_tokendb_contract(db):
    t = db.TokenDB(":memory:")
    t.store_token(ID("tx", 0), b"o1", "USD", "0x64", ["alice"],
                  ledger_token=b"LT", ledger_metadata=b"LM")
    t.store_token(ID("tx", 1), b"o2", "USD", "0x1", ["bob"])
    t.store_token(ID("tx", 2), b"o1", "EUR", "0x5", ["alice"])

    assert t.balance("alice", "USD") == 100
    assert t.balance(None, "USD") == 101
    assert t.is_mine(ID("tx", 0), "alice") and not t.is_mine(ID("tx", 0),
                                                             "bob")
    assert [u.id for u in t.unspent_tokens("alice", "USD")] == [ID("tx", 0)]
    assert t.get_ledger_token(ID("tx", 0)) == (b"LT", b"LM")
    assert t.whose(ID("tx", 0)) == ["alice"]
    assert t.get_token(ID("tx", 0)).quantity == "0x64"

    t.delete_token(ID("tx", 0), spent_by="tx9")
    assert t.balance("alice", "USD") == 0
    assert t.get_token(ID("tx", 0)) is None
    assert t.get_token(ID("tx", 0), include_deleted=True) is not None
    assert t.get_ledger_token(ID("tx", 0)) is None


def test_ttxdb_contract(db):
    d = db.TransactionDB(":memory:")
    rec = TxRecord(tx_id="t1", action_type="transfer", sender="alice",
                   recipient="bob", token_type="USD", amount=5,
                   status=TxStatus.PENDING, timestamp=time.time())
    d.add_transaction(rec)
    d.add_token_request("t1", b"req-bytes")
    assert d.get_token_request("t1") == b"req-bytes"
    assert d.get_status("t1") == TxStatus.PENDING
    d.set_status("t1", TxStatus.CONFIRMED)
    assert d.get_status("t1") == TxStatus.CONFIRMED
    assert d.get_status("missing") == TxStatus.UNKNOWN
    assert [r.tx_id for r in d.query_transactions()] == ["t1"]
    assert d.query_transactions(action_type="issue") == []

    d.add_endorsement_ack("t1", b"endorser", b"sig")
    assert d.get_endorsement_acks("t1") == {b"endorser": b"sig"}

    # statuses filter + validation record: identical across backends
    assert [r.tx_id for r in
            d.query_transactions(statuses=[TxStatus.CONFIRMED])] == ["t1"]
    assert d.query_transactions(statuses=[TxStatus.PENDING]) == []
    d.add_validation_record("t1", b"req", b"meta")
    d.add_validation_record("t2", b"req2")  # metadata optional


def test_auditdb_contract(db):
    a = db.AuditDB(":memory:")
    a.acquire_locks("t1", ["alice", "bob"])
    assert a.locked_eids() == ["alice", "bob"]
    # a second tx cannot lock an already-locked eid
    with pytest.raises(DBError):
        a.acquire_locks("t2", ["bob"])
    # re-acquiring under the same tx is idempotent
    a.acquire_locks("t1", ["alice"])
    a.release_locks("t1")
    assert a.locked_eids() == []

    rec = TxRecord(tx_id="t1", action_type="transfer", sender="alice",
                   recipient="bob", token_type="USD", amount=5,
                   status=TxStatus.CONFIRMED, timestamp=time.time())
    a.add_transaction(rec)
    assert [r.tx_id for r in a.payments("bob")] == ["t1"]
    assert a.payments("charlie") == []
    # payments applies NO action-type filter (sqldb semantics): an issue
    # record with a matching party appears too
    a.add_transaction(TxRecord(tx_id="t2", action_type="issue", sender="",
                               recipient="bob", token_type="USD", amount=1,
                               status=TxStatus.CONFIRMED,
                               timestamp=time.time()))
    assert [r.tx_id for r in a.payments("bob")] == ["t1", "t2"]


def test_tokenlockdb_contract(db):
    lk = db.TokenLockDB(":memory:")
    assert lk.lock(ID("t", 0), "c1")
    assert lk.lock(ID("t", 0), "c1")       # re-entrant for same consumer
    assert not lk.lock(ID("t", 0), "c2")   # held by c1
    assert lk.holder(ID("t", 0)) == "c1"
    lk.unlock_by_consumer("c1")
    assert lk.lock(ID("t", 0), "c2")
    # lease eviction frees stuck locks (sherdlock semantics)
    assert lk.evict_expired(lease_seconds=0.0) == 1
    assert lk.holder(ID("t", 0)) is None


def test_identitydb_contract(db):
    i = db.IdentityDB(":memory:")
    i.register_wallet("alice", "owner", b"id-a")
    i.register_wallet("issuer", "issuer", b"id-i")
    assert i.wallet_identity("alice", "owner") == b"id-a"
    assert i.wallet_identity("alice", "issuer") is None
    assert [(w, r) for w, r, _ in i.wallets("owner")] == [("alice", "owner")]
    i.store_audit_info(b"id-a", b"ai")
    assert i.get_audit_info(b"id-a") == b"ai"
    assert i.get_audit_info(b"missing") is None


def test_concurrent_lock_contract(db):
    """Only one consumer wins each token under concurrency."""
    lk = db.TokenLockDB(":memory:")
    wins = []

    def worker(cid):
        if lk.lock(ID("hot", 0), cid):
            wins.append(cid)

    threads = [threading.Thread(target=worker, args=(f"c{j}",))
               for j in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
