"""Parity: transposed-layout field/EC ops vs the host oracle + ops/field.

The transposed layer (ops/tfield, ops/tec) exists for the Pallas kernels;
its semantics must match ops/field.py and the pure-Python bn254 oracle
bit-for-bit. The fused kernel itself is covered in interpret mode here
(runs the same traced ops on XLA:CPU) and on real hardware by the bench.
"""

import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.ops import ec, limbs as L, pallas_fb, tec
from fabric_token_sdk_tpu.ops import tfield as tf

R_INV = pow(2 ** 256, -1, L.P_INT)
LANE = 8


def _rand_fp(n):
    return [secrets.randbelow(L.P_INT) for _ in range(n)]


def _to_t(vals):
    """Fp ints -> (16, LANE) transposed limb array (no mod-r reduction —
    scalars_to_limbs is for Fr scalars and would corrupt Fp values >= r)."""
    return jnp.asarray(np.stack([L.int_to_limbs(v) for v in vals]).T)


def _col_int(arr, i):
    return L.limbs_to_int(np.asarray(arr)[:, i])


def _pts_to_t(pts):
    arr = L.points_to_projective_limbs(pts)          # (B, 3, 16)
    return jnp.asarray(arr.reshape(len(pts), 48).T)  # (48, B)


def _t_col_point(arr, i) -> bn254.G1:
    return L.projective_limbs_to_point(np.asarray(arr)[:, i].reshape(3, 16))


def _same(p: bn254.G1, q: bn254.G1) -> bool:
    return (p.inf and q.inf) or (not p.inf and not q.inf
                                 and p.x == q.x and p.y == q.y)


@pytest.fixture(scope="module")
def cc():
    return tec.make_consts()


def _rand_pts(n):
    return [bn254.g1_mul(bn254.G1_GENERATOR, secrets.randbelow(bn254.R))
            for _ in range(n)]


class TestTField:
    def test_mont_mul_2d(self, cc):
        av, bv = _rand_fp(LANE), _rand_fp(LANE)
        out = np.asarray(tf.mont_mul(_to_t(av), _to_t(bv), cc.ts))
        for i in range(LANE):
            assert _col_int(out, i) == av[i] * bv[i] * R_INV % L.P_INT

    def test_mont_mul_batch_dims(self, cc):
        av, bv = _rand_fp(LANE), _rand_fp(LANE)
        a3 = jnp.stack([_to_t(av), _to_t(bv)])
        b3 = jnp.stack([_to_t(bv), _to_t(av)])
        out = np.asarray(tf.mont_mul(a3, b3, cc.ts))
        for j in range(2):
            for i in range(LANE):
                assert (L.limbs_to_int(out[j][:, i])
                        == av[i] * bv[i] * R_INV % L.P_INT)

    def test_add_sub_edges(self, cc):
        av = _rand_fp(LANE - 2) + [0, L.P_INT - 1]
        bv = _rand_fp(LANE - 2) + [0, L.P_INT - 1]
        s = np.asarray(tf.add(_to_t(av), _to_t(bv), cc.ts))
        d = np.asarray(tf.sub(_to_t(av), _to_t(bv), cc.ts))
        for i in range(LANE):
            assert _col_int(s, i) == (av[i] + bv[i]) % L.P_INT
            assert _col_int(d, i) == (av[i] - bv[i]) % L.P_INT

    def test_from_mont(self, cc):
        av = _rand_fp(LANE)
        out = np.asarray(tf.from_mont(_to_t(av), cc.ts))
        for i in range(LANE):
            assert _col_int(out, i) == av[i] * R_INV % L.P_INT

    def test_is_zero(self, cc):
        av = [0, 1] + _rand_fp(LANE - 2)
        z = np.asarray(tf.is_zero(_to_t(av)))[0]
        assert list(z) == [v == 0 for v in av]


class TestTEC:
    def test_add_parity_vs_oracle(self, cc):
        p1 = _rand_pts(LANE - 3) + [bn254.G1_IDENTITY]
        p2 = _rand_pts(LANE - 3) + [bn254.G1_IDENTITY]
        p1 += [p1[0], p1[0]]                    # doubling + inverse lanes
        p2 += [p1[0], bn254.g1_neg(p1[0])]
        out = np.asarray(tec.add(_pts_to_t(p1), _pts_to_t(p2), cc))
        for i in range(LANE):
            want = bn254.g1_add(p1[i], p2[i])
            assert _same(_t_col_point(out, i), want), f"lane {i}"

    def test_identity_constant(self, cc):
        idp = np.asarray(tec.identity(4, cc))
        for i in range(4):
            assert _t_col_point(idp, i).inf
        flags = np.asarray(tec.is_identity(jnp.asarray(idp)))[0]
        assert flags.all()

    def test_tree_fold(self, cc):
        pts = _rand_pts(LANE)
        folded = np.asarray(tec.tree_fold(_pts_to_t(pts), cc))
        acc = bn254.G1_IDENTITY
        for p in pts:
            acc = bn254.g1_add(acc, p)
        assert _same(_t_col_point(folded, 0), acc)


class TestFusedFixedBase:
    """Interpret-mode run of the Pallas kernel vs the host oracle.

    The fused kernels now fold over AFFINE tables with mixed addition
    (tec.madd) and a lazy-carry interior: the tables are the 64-plane
    Montgomery-affine form (ec.fixed_base_affine_planes), digit-0 table
    entries are masked in-kernel, and the output must be CANONICAL limbs
    (the final normalize_point is part of the contract)."""

    def test_fold_parity(self):
        T, B = 3, 4
        gens = [bn254.g1_mul(bn254.G1_GENERATOR, 7 + i) for i in range(T)]
        gen_dev = jnp.asarray(L.points_to_projective_limbs(gens))
        planes = ec.fixed_base_affine_planes(gen_dev)   # (T, 32, 256, 64)
        sc_int = [[secrets.randbelow(bn254.R) for _ in range(T)]
                  for _ in range(B)]
        sc_int[1][0] = 0        # all-digit-0 lane: identity via the mask
        scalars = jnp.asarray(np.stack(
            [L.scalars_to_limbs(row) for row in sc_int]))   # (B, T, 16)
        planes_t = pallas_fb.transpose_planes(planes)
        got = np.asarray(pallas_fb.fixed_base_gather_fused(
            planes_t, scalars, interpret=True))
        # bit-identical contract: lazy carries fully resolved on readback
        assert int(got.max()) < (1 << 16)
        for b in range(B):
            for t in range(T):
                want = bn254.g1_mul(gens[t], sc_int[b][t])
                pt = L.projective_limbs_to_point(got[b, t])
                assert _same(pt, want), (b, t)

    def test_msm_parity(self):
        T, B = 4, 3
        gens = [bn254.g1_mul(bn254.G1_GENERATOR, 11 + i) for i in range(T)]
        gen_dev = jnp.asarray(L.points_to_projective_limbs(gens))
        planes = ec.fixed_base_affine_planes(gen_dev)
        sc_int = [[secrets.randbelow(bn254.R) for _ in range(T)]
                  for _ in range(B)]
        scalars = jnp.asarray(np.stack(
            [L.scalars_to_limbs(row) for row in sc_int]))
        got = np.asarray(pallas_fb.fixed_base_msm_fused(
            pallas_fb.transpose_planes(planes), scalars, interpret=True))
        assert int(got.max()) < (1 << 16)
        for b in range(B):
            want = bn254.msm(gens, sc_int[b])
            pt = L.projective_limbs_to_point(got[b])
            assert _same(pt, want), b


class TestFusedVarMSM:
    """Interpret-mode run of the variable-base Horner kernel."""

    def test_var_msm_parity(self):
        V = 7   # pads to VAR_BLOCK with identity terms
        pts = _rand_pts(V - 1) + [bn254.G1_IDENTITY]
        sc = [secrets.randbelow(bn254.R) for _ in range(V)]
        got = np.asarray(pallas_fb.msm_var_fused(
            jnp.asarray(L.points_to_projective_limbs(pts)),
            jnp.asarray(L.scalars_to_limbs(sc)), interpret=True))
        want = bn254.msm(pts[:-1], sc[:-1])
        assert _same(L.projective_limbs_to_point(got), want)

    def test_mul2_rows_parity(self):
        """Per-row paired mul (the K-equation's x*D + C) vs the host
        oracle: includes an identity point, a zero scalar, and a
        scalar-1 row; B pads to the kernel's row block."""
        B = 5
        pts = _rand_pts(2 * B)
        pts[3] = bn254.G1_IDENTITY
        sc = [secrets.randbelow(bn254.R) for _ in range(2 * B)]
        sc[1] = 1
        sc[4] = 0
        proj = jnp.asarray(
            L.points_to_projective_limbs(pts).reshape(B, 2, 3, 16))
        sc_l = jnp.asarray(L.scalars_to_limbs(sc).reshape(B, 2, 16))
        got = np.asarray(
            pallas_fb.mul2_rows_fused(proj, sc_l, interpret=True))
        for b in range(B):
            want = bn254.msm(pts[2 * b:2 * b + 2], sc[2 * b:2 * b + 2])
            assert _same(L.projective_limbs_to_point(got[b]), want), b
