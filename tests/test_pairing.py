"""BN254 pairing + Idemix credential-chain tests.

Covers the capability the reference exercises through IBM/idemix
(token/services/identity/idemix/km.go:46-365): issuer-certified attributes,
unlinkable possession proofs, and the auditor's NymEID inspection on top.
"""

import copy

import pytest

from fabric_token_sdk_tpu.crypto import bn254
from fabric_token_sdk_tpu.crypto import pairing as pr
from fabric_token_sdk_tpu.crypto.bn254 import (fr_rand, g1_add, g1_mul,
                                               g1_neg)
from fabric_token_sdk_tpu.services.identity import credential as cr
from fabric_token_sdk_tpu.services.identity import idemix as ix


# ---------------------------------------------------------------------------
# pairing layer
# ---------------------------------------------------------------------------

class TestPairing:
    def test_g2_generator_on_twist_and_in_subgroup(self):
        assert pr.g2_is_on_curve(pr.G2_GENERATOR)
        assert pr.g2_in_subgroup(pr.G2_GENERATOR)

    def test_g2_group_laws(self):
        q = pr.G2_GENERATOR
        assert pr.g2_add(q, None) == q
        assert pr.g2_add(None, q) == q
        assert pr.g2_add(q, pr.g2_neg(q)) is None
        assert pr.g2_mul(q, 5) == pr.g2_add(
            pr.g2_mul(q, 2), pr.g2_mul(q, 3))
        assert pr.g2_mul(q, bn254.R) is None

    def test_bilinearity(self):
        p1, q = bn254.G1_GENERATOR, pr.G2_GENERATOR
        e = pr.pairing(p1, q)
        assert e != pr.FP12_ONE                      # non-degenerate
        assert pr.pairing(g1_mul(p1, 2), q) == pr.fp12_mul(e, e)
        assert pr.pairing(p1, pr.g2_mul(q, 2)) == pr.fp12_mul(e, e)
        assert pr.pairing(g1_mul(p1, 3), pr.g2_mul(q, 5)) \
            == pr.fp12_pow(e, 15)

    def test_gt_has_order_r(self):
        e = pr.pairing(bn254.G1_GENERATOR, pr.G2_GENERATOR)
        assert pr.fp12_pow(e, bn254.R) == pr.FP12_ONE

    def test_pairing_product_and_identity_inputs(self):
        p1, q = bn254.G1_GENERATOR, pr.G2_GENERATOR
        assert pr.pairing_product_is_one([(p1, q), (g1_neg(p1), q)])
        assert not pr.pairing_product_is_one([(p1, q), (p1, q)])
        assert pr.pairing(None, q) == pr.FP12_ONE
        assert pr.pairing(p1, None) == pr.FP12_ONE

    def test_g2_serialization_round_trip(self):
        q = pr.g2_mul(pr.G2_GENERATOR, 123456789)
        raw = cr._g2_to_bytes(q)
        assert cr._g2_from_bytes(raw) == q
        assert cr._g2_from_bytes(bytes(128)) is None
        # off-subgroup point must be rejected: a point on the twist with
        # cofactor component (generate by using a curve point not in E'[r])
        bad = raw[:-1] + bytes([raw[-1] ^ 1])
        with pytest.raises(cr.CredentialError):
            cr._g2_from_bytes(bad)


# ---------------------------------------------------------------------------
# credential scheme
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def issuer():
    return cr.IssuerKey.generate(4)


@pytest.fixture(scope="module")
def holder(issuer):
    sk = fr_rand()
    nonce = b"n-0"
    req = cr.CredentialRequest.create(issuer.public, sk, nonce)
    attrs = [cr.attr_to_zr(v)
             for v in ("org1", "member", "alice@org1", "rh-1")]
    cred = cr.issue_credential(issuer, req, nonce, attrs)
    return sk, cred


class TestCredential:
    def test_issue_and_holder_verify(self, issuer, holder):
        sk, cred = holder
        cred.verify(issuer.public, sk)
        with pytest.raises(cr.CredentialError):
            cred.verify(issuer.public, fr_rand())   # wrong sk

    def test_request_pok_rejects_replay_nonce(self, issuer):
        sk = fr_rand()
        req = cr.CredentialRequest.create(issuer.public, sk, b"n-1")
        with pytest.raises(cr.CredentialError):
            req.verify(issuer.public, b"n-2")

    def test_presentation_round_trip(self, issuer, holder):
        sk, cred = holder
        ipk = issuer.public
        r_nym = fr_rand()
        nym = g1_add(g1_mul(ipk.h_sk, sk), g1_mul(ipk.h_rand, r_nym))
        pres = cr.present(ipk, cred, sk, nym, r_nym, {0, 1}, b"m")
        cr.verify_presentation(ipk, pres, nym, b"m")
        # serialization is stable and verifies after a round trip
        raw = pres.serialize()
        again = cr.Presentation.deserialize(raw)
        cr.verify_presentation(ipk, again, nym, b"m")
        assert again.serialize() == raw

    def test_presentation_discloses_only_requested(self, issuer, holder):
        sk, cred = holder
        ipk = issuer.public
        r_nym = fr_rand()
        nym = g1_add(g1_mul(ipk.h_sk, sk), g1_mul(ipk.h_rand, r_nym))
        pres = cr.present(ipk, cred, sk, nym, r_nym, {0}, b"m")
        assert set(pres.disclosed) == {0}
        assert set(pres.s_hidden) == {1, 2, 3}
        cr.verify_presentation(ipk, pres, nym, b"m")

    def test_presentation_rejections(self, issuer, holder):
        sk, cred = holder
        ipk = issuer.public
        r_nym = fr_rand()
        nym = g1_add(g1_mul(ipk.h_sk, sk), g1_mul(ipk.h_rand, r_nym))
        pres = cr.present(ipk, cred, sk, nym, r_nym, {0, 1}, b"m")

        with pytest.raises(cr.CredentialError):     # wrong message
            cr.verify_presentation(ipk, pres, nym, b"other")
        with pytest.raises(cr.CredentialError):     # wrong nym
            other = g1_add(g1_mul(ipk.h_sk, fr_rand()),
                           g1_mul(ipk.h_rand, r_nym))
            cr.verify_presentation(ipk, other and pres, other, b"m")
        mutated = copy.deepcopy(pres)               # tampered attribute
        mutated.disclosed[0] = cr.attr_to_zr("org2")
        with pytest.raises(cr.CredentialError):
            cr.verify_presentation(ipk, mutated, nym, b"m")
        mutated = copy.deepcopy(pres)               # missing hidden slot
        del mutated.s_hidden[2]
        with pytest.raises(cr.CredentialError):
            cr.verify_presentation(ipk, mutated, nym, b"m")

    def test_identity_aprime_forgery_rejected(self, issuer, holder):
        """The classic BBS+ forgery A' = Abar = O makes the pairing check
        trivially true; the verifier must reject identity A' outright —
        and bn254 spells the identity as G1(0,0,inf), not None."""
        sk, cred = holder
        ipk = issuer.public
        r_nym = fr_rand()
        nym = g1_add(g1_mul(ipk.h_sk, sk), g1_mul(ipk.h_rand, r_nym))
        pres = cr.present(ipk, cred, sk, nym, r_nym, {0}, b"m")
        forged = copy.deepcopy(pres)
        forged.a_prime = bn254.G1_IDENTITY
        forged.a_bar = bn254.G1_IDENTITY
        with pytest.raises(cr.CredentialError, match="identity"):
            cr.verify_presentation(ipk, forged, nym, b"m")

    def test_pairing_identity_inputs_are_neutral(self):
        """e(O, Q) = 1 for BOTH identity spellings (None and inf=True)."""
        q = pr.G2_GENERATOR
        assert pr.pairing(bn254.G1_IDENTITY, q) == pr.FP12_ONE
        assert pr.pairing(None, q) == pr.FP12_ONE
        zero_sum = g1_add(bn254.G1_GENERATOR, g1_neg(bn254.G1_GENERATOR))
        assert pr.pairing_product_is_one([(zero_sum, q)])

    def test_wrong_issuer_credential_fails_pairing(self, issuer):
        rogue = cr.IssuerKey.generate(4)
        sk = fr_rand()
        req = cr.CredentialRequest.create(rogue.public, sk, b"n")
        attrs = [cr.attr_to_zr(v) for v in ("a", "b", "c", "d")]
        forged = cr.issue_credential(rogue, req, b"n", attrs)
        ipk = issuer.public
        r_nym = fr_rand()
        nym = g1_add(g1_mul(ipk.h_sk, sk), g1_mul(ipk.h_rand, r_nym))
        pres = cr.present(ipk, forged, sk, nym, r_nym, {0}, b"m")
        with pytest.raises(cr.CredentialError,
                           match="pairing|proof"):
            cr.verify_presentation(ipk, pres, nym, b"m")

    def test_unlinkability_shape(self, issuer, holder):
        """Two presentations share no group element (re-randomized)."""
        sk, cred = holder
        ipk = issuer.public
        outs = []
        for _ in range(2):
            r_nym = fr_rand()
            nym = g1_add(g1_mul(ipk.h_sk, sk), g1_mul(ipk.h_rand, r_nym))
            pres = cr.present(ipk, cred, sk, nym, r_nym, {0, 1}, b"m")
            outs.append((pres.a_prime, pres.a_bar, pres.d, nym))
        for a, b in zip(*outs):
            assert a != b


# ---------------------------------------------------------------------------
# idemix integration (credential mode)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def authority():
    return ix.EnrollmentAuthority(with_credentials=True)


@pytest.fixture(scope="module")
def km(authority):
    return ix.IdemixKeyManager("alice@org1", authority,
                               ou="org1", role="member")


class TestIdemixCredentialMode:
    def test_pseudonym_carries_valid_possession_proof(self, authority, km):
        p = km.fresh_pseudonym()
        ident = bytes(p.identity())
        from fabric_token_sdk_tpu.services.identity import typed as t
        ti = t.unmarshal_typed_identity(ident)
        verifier = ix.CredentialIdentityVerifier(
            authority.issuer_public_key)
        disclosed = verifier.validate(ti.identity)
        assert disclosed[ix.ATTR_OU] == cr.attr_to_zr("org1")
        assert disclosed[ix.ATTR_ROLE] == cr.attr_to_zr("member")
        assert ix.ATTR_EID not in disclosed          # EID stays hidden

    def test_nym_signature_in_credential_mode(self, km):
        p = km.fresh_pseudonym()
        ident = bytes(p.identity())
        sig = km.sign(ident, b"tx-payload")
        from fabric_token_sdk_tpu.services.identity import typed as t
        ti = t.unmarshal_typed_identity(ident)
        ix.NymVerifier.from_typed(ti.identity).verify(b"tx-payload", sig)
        with pytest.raises(ix.IdemixError):
            ix.NymVerifier.from_typed(ti.identity).verify(b"other", sig)

    def test_uncredentialed_identity_rejected(self, authority):
        """A dlog-only pseudonym fails credential-mode validation: the
        'any enrolled key can self-issue pseudonyms' hole is closed."""
        plain_authority = ix.EnrollmentAuthority()
        outsider = ix.IdemixKeyManager("mallory", plain_authority)
        p = outsider.fresh_pseudonym()
        from fabric_token_sdk_tpu.services.identity import typed as t
        ti = t.unmarshal_typed_identity(bytes(p.identity()))
        verifier = ix.CredentialIdentityVerifier(
            authority.issuer_public_key)
        with pytest.raises(ix.IdemixError, match="no credential proof"):
            verifier.validate(ti.identity)

    def test_audit_matcher_still_works(self, authority, km):
        p = km.fresh_pseudonym()
        ident = bytes(p.identity())
        info = km.audit_info(ident)
        matcher = ix.IdemixInfoMatcher(authority.ca_identity())
        matcher.match_identity(ident, info)
        assert matcher.enrollment_id(info) == "alice@org1"
        # audit info from a different pseudonym must not match
        other = km.fresh_pseudonym()
        with pytest.raises(ix.IdemixError):
            matcher.match_identity(bytes(other.identity()), info)
