"""Durability + restart recovery (SURVEY.md §5 checkpoint/resume).

The reference's durable state is its SQL DBs + the ledger; integration
suites restart live nodes mid-test and assert state reconstruction
(fungible/tests.go:329 Restart). Here: file-backed sqlite stores survive a
node object being torn down and rebuilt, and a node that was OFFLINE for a
commit reconstructs its tokens from the ledger on the next scan.
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.db.sqldb import TokenDB, TxStatus
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus


@pytest.fixture
def world(tmp_path):
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    validator = fabtoken.new_validator(pp, Deserializer())
    ledger = MemoryLedger()
    cc = TokenChaincode(validator, ledger, pp.serialize())
    return dict(cc=cc, issuer_keys=issuer_keys, auditor_keys=auditor_keys,
                tmp=tmp_path)


def _mknet(world, alice_keys, bus=None):
    bus = bus or SessionBus()
    cc = world["cc"]
    nodes = {
        "issuer": TokenNode("issuer", world["issuer_keys"], bus, cc,
                            auditor_name="auditor"),
        "auditor": AuditorNode("auditor", world["auditor_keys"], bus, cc,
                               auditor_name="auditor"),
        "alice": TokenNode("alice", alice_keys, bus, cc,
                           auditor_name="auditor",
                           db_path_prefix=str(world["tmp"] / "alice")),
        "bob": TokenNode("bob", new_signing_identity(), bus, cc,
                         auditor_name="auditor"),
    }
    return nodes


def test_restart_preserves_tokens_and_ttx_state(world):
    alice_keys = new_signing_identity()
    net = _mknet(world, alice_keys)
    alice = net["alice"]
    tx = alice.issue("issuer", "alice", "USD", hex(120))
    assert alice.execute(tx).status == "VALID"
    assert alice.balance("USD") == 120
    assert alice.ttxdb.get_status(tx.tx_id) == TxStatus.CONFIRMED

    # "restart": tear down every node object, rebuild over the same ledger
    # and the same on-disk DBs (fungible/tests.go:329 Restart semantics)
    world["cc"].ledger.listeners.clear()
    net2 = _mknet(world, alice_keys)
    alice2 = net2["alice"]
    assert alice2.balance("USD") == 120
    assert alice2.ttxdb.get_status(tx.tx_id) == TxStatus.CONFIRMED

    # and the restarted node can SPEND its recovered tokens
    tx2 = alice2.transfer("USD", hex(50), "bob")
    assert alice2.execute(tx2).status == "VALID"
    assert alice2.balance("USD") == 70
    assert net2["bob"].balance("USD") == 50


def test_offline_node_recovers_from_ledger_scan(world):
    """Tokens are re-derivable from the ledger (SURVEY §5): a node that
    missed the commit ingests by scanning, including past redeem gaps."""
    alice_keys = new_signing_identity()
    net = _mknet(world, alice_keys)
    alice, bob = net["alice"], net["bob"]
    tx = alice.issue("issuer", "alice", "USD", hex(30))
    assert alice.execute(tx).status == "VALID"

    # bob goes offline (loses his listener) while alice pays him
    world["cc"].ledger.remove_finality_listener(bob._on_commit)
    tx2 = alice.transfer("USD", hex(10), "bob")
    ev = alice.execute(tx2)
    assert ev.status == "VALID"
    assert bob.balance("USD") == 0  # missed it

    # back online: replay the missed block's event through the scan path
    bob._on_commit(ev)
    assert bob.balance("USD") == 10


def test_tokendb_file_roundtrip(tmp_path):
    from fabric_token_sdk_tpu.token.model import ID

    path = str(tmp_path / "t.sqlite")
    db = TokenDB(path)
    db.store_token(ID("tx", 0), b"o", "USD", "0x5", ["w"])
    db.close()
    db2 = TokenDB(path)
    toks = db2.unspent_tokens("w")
    assert len(toks) == 1 and toks[0].quantity == "0x5"
