"""Network front door (serve/rpc.py + serve/rpc_client.py): frame
codec adversity, credit backpressure, deadline shedding, draining
GOAWAY stop, and reconnect-after-restart.

Everything runs crypto-free on :class:`StubZK` so this is tier-1: the
server + ``VerificationService`` live on a background-thread event
loop, the real ``RpcClient`` dials it over loopback TCP, and the
adversity cases speak raw bytes on plain sockets.

The invariant under test throughout: a poisoned stream is a *counted*
``rpc_frame_errors_total{kind}`` increment and the loss of that one
connection — never a hang, and never the accept loop.
"""

import asyncio
import socket
import struct
import threading
import time
import zlib

import numpy as np
import pytest

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.serve import (RpcClient, RpcConfig, RpcServer,
                                        ServeConfig, StubZK,
                                        VerificationService,
                                        WorkerUnavailable)
from fabric_token_sdk_tpu.serve.config import LANE_INTERACTIVE
from fabric_token_sdk_tpu.serve.rpc import (HELLO, MAGIC, PING, WELCOME,
                                            recv_frame_sock, send_frame_sock)

_HEADER = struct.Struct(">BBHII")


# ------------------------------------------------------------- harness
class _Harness:
    """Service + RpcServer on a background-thread event loop."""

    def __init__(self, serve_cfg=None, rpc_cfg=None):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self.loop.run_forever, name="rpc-test-loop", daemon=True)
        self._thread.start()
        serve_cfg = serve_cfg or ServeConfig(buckets=(8,), max_wait_s=0.002)

        async def _boot():
            svc = VerificationService(StubZK(), serve_cfg)
            await svc.start(prewarm=False)
            server = RpcServer(svc, rpc_cfg)
            addr = await server.start()
            return svc, server, addr

        self.svc, self.server, self.address = self.run(_boot())
        self._stopped = False

    def run(self, coro, timeout=30.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop) \
            .result(timeout)

    def stop_server(self):
        self.run(self.server.stop(drain=True))

    def stop(self):
        if self._stopped:
            return
        self._stopped = True

        async def _down():
            await self.server.stop(drain=True)
            await self.svc.stop(drain=True)

        self.run(_down())
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5.0)
        self.loop.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def _client(addr, **kw):
    kw.setdefault("redial_attempts", 2)
    kw.setdefault("redial_base_s", 0.01)
    kw.setdefault("redial_cap_s", 0.05)
    kw.setdefault("call_timeout_s", 20.0)
    return RpcClient(addr, **kw)


def _count(name, **labels):
    """Sum a family across label sets matching ``labels`` (counters and
    gauges numeric; histograms count their observations)."""
    total = 0
    for (fam, lab), val in GLOBAL.snapshot().items():
        if fam != name:
            continue
        had = dict(lab)
        if any(had.get(k) != v for k, v in labels.items()):
            continue
        total += val["count"] if isinstance(val, dict) else val
    return total


def _await_count(name, minimum=1, timeout=5.0, **labels):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _count(name, **labels) >= minimum:
            return
        time.sleep(0.01)
    raise AssertionError(
        f"{name}{labels} stuck at {_count(name, **labels)} < {minimum}")


def _raw_conn(addr):
    sock = socket.create_connection(addr, timeout=5.0)
    sock.settimeout(1.0)
    return sock


def _handshake(addr, tms="raw"):
    """Plain-socket HELLO/WELCOME so a test can then misbehave."""
    sock = _raw_conn(addr)
    send_frame_sock(sock, HELLO, {"tms_id": tms, "t": time.time()})
    frame = recv_frame_sock(sock, body_timeout_s=5.0)
    assert frame is not None and frame[0] == WELCOME
    return sock


def _assert_server_alive(addr):
    """The accept loop survived: a fresh well-behaved client round-trips."""
    cli = _client(addr, tms_id="prober")
    try:
        out = cli.submit_range([True, False], [None, None])
        assert out.tolist() == [True, False]
    finally:
        cli.close()


# ------------------------------------------------------------ happy path
def test_range_and_block_roundtrip():
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="alpha")
        try:
            out = cli._range.verify([True, False, True, True], [None] * 4)
            assert isinstance(out, np.ndarray) and out.dtype == bool
            assert out.tolist() == [True, False, True, True]

            t_ok, i_ok = cli.verify_block(
                [(True, [], []), (False, [], [])], [(True, [])])
            assert t_ok.tolist() == [True, False]
            assert i_ok.tolist() == [True]

            # handshake measured a round trip and granted credits
            assert cli.rtt_s >= 0.0
            assert cli.ping(timeout_s=5.0)
            assert cli.alive()

            # a write in flight holds started > done for one loop tick;
            # settled accounting must converge
            deadline = time.monotonic() + 5.0
            while True:
                (conn,) = h.server.status()["connections"].values()
                if conn["frames_started"] == conn["frames_done"]:
                    break
                assert time.monotonic() < deadline, conn
                time.sleep(0.01)
            assert conn["tms_id"] == "alpha"
        finally:
            cli.close()
        assert _count("rpc_requests_total", tms="alpha", kind="range") == 1
        assert _count("rpc_requests_total", tms="alpha", kind="block") == 1
        assert _count("rpc_frame_errors_total") == 0
        assert h.server.frames_clean


def test_multi_tenant_labels_on_shared_server():
    GLOBAL.reset()
    with _Harness() as h:
        clients = [_client(h.address, tms_id=t) for t in ("alice", "bob")]
        try:
            for cli in clients:
                assert cli.submit_range([True], [None]).tolist() == [True]
        finally:
            for cli in clients:
                cli.close()
        for tenant in ("alice", "bob"):
            assert _count("rpc_connections_total", tms=tenant) == 1
            assert _count("rpc_requests_total", tms=tenant,
                          kind="range") == 1


# -------------------------------------------- deadlines and backpressure
def test_expired_deadline_shed_at_decode():
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address)
        try:
            cli.wait_ready(timeout_s=10.0)
            # simulate clock skew: the wire deadline lands in the
            # server's past, so the SUBMIT is shed at decode
            cli.clock_offset_s = -30.0
            with pytest.raises(WorkerUnavailable, match="expired"):
                cli.submit_range([True], [None], deadline_s=5.0)
        finally:
            cli.close()
        assert _count("rpc_deadline_expired_total") == 1
        # shed before entering the service, so never counted as accepted
        assert _count("rpc_requests_total", kind="range") == 0
        _assert_server_alive(h.address)


def test_credit_backpressure_stalls_then_sheds():
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(conn_credits=2)) as h:
        cli = _client(h.address, credit_wait_s=0.3)
        try:
            # 5 rows can never fit a 2-credit grant: the client stalls
            # on credits (counted) and sheds as transient backpressure
            with pytest.raises(WorkerUnavailable, match="backpressure"):
                cli.submit_range([True] * 5, [None] * 5)
            assert _count("rpc_credit_waits_total") >= 1
            # a batch within budget still flows, and the RESULT's
            # replenish restores the grant for the next one
            for _ in range(3):
                out = cli.submit_range([True, True], [None, None])
                assert out.tolist() == [True, True]
        finally:
            cli.close()


def test_hedged_interactive_send_first_reply_wins():
    GLOBAL.reset()
    with _Harness(serve_cfg=ServeConfig(buckets=(8,), max_wait_s=0.05)) as h:
        cli = _client(h.address, hedge_after_s=0.0)
        try:
            out = cli.submit_range([True, False], [None, None],
                                   lane=LANE_INTERACTIVE)
            assert out.tolist() == [True, False]
        finally:
            cli.close()
        assert _count("rpc_hedges_total") >= 1


# ------------------------------------------------------- frame adversity
@pytest.mark.parametrize("kind,frame_bytes", [
    ("bad_magic", b"\x00" * 12),
    ("oversize", _HEADER.pack(MAGIC, HELLO, 0, 2**31 - 1, 0)),
    ("checksum", _HEADER.pack(MAGIC, HELLO, 0, 4, 0xDEAD) + b"ruin"),
    ("decode", _HEADER.pack(MAGIC, HELLO, 0, 4,
                            zlib.crc32(b"ruin")) + b"ruin"),
    ("torn", _HEADER.pack(MAGIC, HELLO, 0, 64, 0)[:6]),
])
def test_poisoned_hello_is_counted_not_fatal(kind, frame_bytes):
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(hello_timeout_s=1.0)) as h:
        sock = _raw_conn(h.address)
        try:
            sock.sendall(frame_bytes)
        finally:
            sock.close()  # "torn" needs the close; harmless for the rest
        _await_count("rpc_frame_errors_total", kind=kind)
        _assert_server_alive(h.address)
        assert h.server.frames_clean


def test_first_frame_must_be_hello():
    GLOBAL.reset()
    with _Harness() as h:
        sock = _raw_conn(h.address)
        try:
            send_frame_sock(sock, PING, {"t": time.time()})
            _await_count("rpc_frame_errors_total", kind="protocol")
        finally:
            sock.close()
        _assert_server_alive(h.address)


def test_midframe_disconnect_after_handshake():
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(frame_timeout_s=1.0)) as h:
        sock = _handshake(h.address)
        # half a SUBMIT frame, then vanish
        sock.sendall(_HEADER.pack(MAGIC, 3, 0, 128, 0) + b"x" * 40)
        sock.close()
        _await_count("rpc_frame_errors_total", kind="torn")
        _assert_server_alive(h.address)


def test_slow_loris_frame_hits_deadline_not_a_hang():
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(frame_timeout_s=0.4,
                                    idle_tick_s=0.1)) as h:
        sock = _handshake(h.address)
        try:
            # declare a 100B payload, trickle 10B, stall past the
            # frame deadline: the server must fail it as slow_frame
            # within frame_timeout_s, not park in recv forever
            sock.sendall(_HEADER.pack(MAGIC, 3, 0, 100, 0) + b"y" * 10)
            _await_count("rpc_frame_errors_total", kind="slow_frame",
                         timeout=5.0)
        finally:
            sock.close()
        _assert_server_alive(h.address)


# ----------------------------------------------------- drain and restart
def test_draining_stop_under_load_closes_no_frame_midwrite():
    GLOBAL.reset()
    with _Harness(serve_cfg=ServeConfig(buckets=(8,), max_wait_s=0.05)) as h:
        cli = _client(h.address)
        results, sheds = [], []

        def _caller():
            try:
                results.append(
                    cli.submit_range([True] * 8, [None] * 8).tolist())
            except WorkerUnavailable as exc:
                sheds.append(exc)

        threads = [threading.Thread(target=_caller) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.02)  # let submits get in flight
            h.stop_server()
            for t in threads:
                t.join(timeout=30.0)
            assert not any(t.is_alive() for t in threads)
            # every call resolved: served before the drain, or shed as
            # transient (goaway) for the caller's ladder to retry
            assert len(results) + len(sheds) == 4
            for verdicts in results:
                assert verdicts == [True] * 8
            # THE invariant: nothing was cut mid-frame by the drain
            assert h.server.frames_clean
            assert _count("rpc_frame_errors_total", kind="midframe_close") \
                == 0
            assert _count("rpc_goaways_total", role="server") >= 1
            # the listener is gone now, so a fresh call exhausts the
            # redial ladder into WorkerUnavailable — never a hang
            with pytest.raises(WorkerUnavailable):
                cli.submit_range([True], [None])
        finally:
            cli.close()


def test_client_reconnects_after_server_restart_on_same_port():
    GLOBAL.reset()
    first = _Harness()
    host, port = first.address
    cli = _client((host, port), redial_attempts=6, redial_cap_s=0.2)
    try:
        assert cli.submit_range([True], [None]).tolist() == [True]
        first.stop()
        with pytest.raises(WorkerUnavailable):
            cli.submit_range([True], [None])
        with _Harness(rpc_cfg=RpcConfig(port=port)) as second:
            assert second.address[1] == port
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    out = cli.submit_range([True, False], [None, None])
                    break
                except WorkerUnavailable:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.05)
            assert out.tolist() == [True, False]
    finally:
        cli.close()
    assert _count("rpc_redials_total", outcome="ok") >= 2
    assert _count("rpc_redials_total", outcome="error") >= 1
