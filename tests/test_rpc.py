"""Network front door (serve/rpc.py + serve/rpc_client.py): frame
codec adversity, credit backpressure, deadline shedding, draining
GOAWAY stop, reconnect-after-restart, and the columnar zero-copy
SUBMIT_BATCH ingest path (codec round-trip, poisoned-batch rejection,
capability negotiation, client-side coalescing).

Everything runs crypto-free on :class:`StubZK` so this is tier-1: the
server + ``VerificationService`` live on a background-thread event
loop, the real ``RpcClient`` dials it over loopback TCP, and the
adversity cases speak raw bytes on plain sockets. Columnar frames use
``FMT_OPAQUE`` rows (truth words), so the batch codec is exercised
without the pairing stack.

The invariant under test throughout: a poisoned stream is a *counted*
``rpc_frame_errors_total{kind}`` increment and the loss of that one
connection — never a hang, and never the accept loop.
"""

import asyncio
import pickle
import random
import socket
import struct
import threading
import time
import zlib

import numpy as np
import pytest

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.serve import (BatchSubmitBuffer, ColumnarError,
                                        RpcClient, RpcConfig, RpcServer,
                                        ServeConfig, StubZK,
                                        VerificationService,
                                        WorkerUnavailable)
from fabric_token_sdk_tpu.serve.columnar import (FMT_OPAQUE,
                                                 decode_submit_batch,
                                                 encode_submit_batch,
                                                 materialize_rows,
                                                 opaque_cells)
from fabric_token_sdk_tpu.serve.config import LANE_BULK, LANE_INTERACTIVE
from fabric_token_sdk_tpu.serve.rpc import (HELLO, MAGIC, PING,
                                            SUBMIT_BATCH, WELCOME,
                                            encode_raw_frame,
                                            recv_frame_sock,
                                            send_frame_sock)

_HEADER = struct.Struct(">BBHII")


# ------------------------------------------------------------- harness
class _Harness:
    """Service + RpcServer on a background-thread event loop."""

    def __init__(self, serve_cfg=None, rpc_cfg=None):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self.loop.run_forever, name="rpc-test-loop", daemon=True)
        self._thread.start()
        serve_cfg = serve_cfg or ServeConfig(buckets=(8,), max_wait_s=0.002)

        async def _boot():
            svc = VerificationService(StubZK(), serve_cfg)
            await svc.start(prewarm=False)
            server = RpcServer(svc, rpc_cfg)
            addr = await server.start()
            return svc, server, addr

        self.svc, self.server, self.address = self.run(_boot())
        self._stopped = False

    def run(self, coro, timeout=30.0):
        return asyncio.run_coroutine_threadsafe(coro, self.loop) \
            .result(timeout)

    def stop_server(self):
        self.run(self.server.stop(drain=True))

    def stop(self):
        if self._stopped:
            return
        self._stopped = True

        async def _down():
            await self.server.stop(drain=True)
            await self.svc.stop(drain=True)

        self.run(_down())
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5.0)
        self.loop.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def _client(addr, **kw):
    kw.setdefault("redial_attempts", 2)
    kw.setdefault("redial_base_s", 0.01)
    kw.setdefault("redial_cap_s", 0.05)
    kw.setdefault("call_timeout_s", 20.0)
    return RpcClient(addr, **kw)


def _count(name, **labels):
    """Sum a family across label sets matching ``labels`` (counters and
    gauges numeric; histograms count their observations)."""
    total = 0
    for (fam, lab), val in GLOBAL.snapshot().items():
        if fam != name:
            continue
        had = dict(lab)
        if any(had.get(k) != v for k, v in labels.items()):
            continue
        total += val["count"] if isinstance(val, dict) else val
    return total


def _await_count(name, minimum=1, timeout=5.0, **labels):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _count(name, **labels) >= minimum:
            return
        time.sleep(0.01)
    raise AssertionError(
        f"{name}{labels} stuck at {_count(name, **labels)} < {minimum}")


def _raw_conn(addr):
    sock = socket.create_connection(addr, timeout=5.0)
    sock.settimeout(1.0)
    return sock


def _handshake(addr, tms="raw"):
    """Plain-socket HELLO/WELCOME so a test can then misbehave."""
    sock = _raw_conn(addr)
    send_frame_sock(sock, HELLO, {"tms_id": tms, "t": time.time()})
    frame = recv_frame_sock(sock, body_timeout_s=5.0)
    assert frame is not None and frame[0] == WELCOME
    return sock


def _assert_server_alive(addr):
    """The accept loop survived: a fresh well-behaved client round-trips."""
    cli = _client(addr, tms_id="prober")
    try:
        out = cli.submit_range([True, False], [None, None])
        assert out.tolist() == [True, False]
    finally:
        cli.close()


# ------------------------------------------------------------ happy path
def test_range_and_block_roundtrip():
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="alpha")
        try:
            out = cli._range.verify([True, False, True, True], [None] * 4)
            assert isinstance(out, np.ndarray) and out.dtype == bool
            assert out.tolist() == [True, False, True, True]

            t_ok, i_ok = cli.verify_block(
                [(True, [], []), (False, [], [])], [(True, [])])
            assert t_ok.tolist() == [True, False]
            assert i_ok.tolist() == [True]

            # handshake measured a round trip and granted credits
            assert cli.rtt_s >= 0.0
            assert cli.ping(timeout_s=5.0)
            assert cli.alive()

            # a write in flight holds started > done for one loop tick;
            # settled accounting must converge
            deadline = time.monotonic() + 5.0
            while True:
                (conn,) = h.server.status()["connections"].values()
                if conn["frames_started"] == conn["frames_done"]:
                    break
                assert time.monotonic() < deadline, conn
                time.sleep(0.01)
            assert conn["tms_id"] == "alpha"
        finally:
            cli.close()
        assert _count("rpc_requests_total", tms="alpha", kind="range") == 1
        assert _count("rpc_requests_total", tms="alpha", kind="block") == 1
        assert _count("rpc_frame_errors_total") == 0
        assert h.server.frames_clean


def test_multi_tenant_labels_on_shared_server():
    GLOBAL.reset()
    with _Harness() as h:
        clients = [_client(h.address, tms_id=t) for t in ("alice", "bob")]
        try:
            for cli in clients:
                assert cli.submit_range([True], [None]).tolist() == [True]
        finally:
            for cli in clients:
                cli.close()
        for tenant in ("alice", "bob"):
            assert _count("rpc_connections_total", tms=tenant) == 1
            assert _count("rpc_requests_total", tms=tenant,
                          kind="range") == 1


# -------------------------------------------- deadlines and backpressure
def test_expired_deadline_shed_at_decode():
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address)
        try:
            cli.wait_ready(timeout_s=10.0)
            # simulate clock skew: the wire deadline lands in the
            # server's past, so the SUBMIT is shed at decode
            cli.clock_offset_s = -30.0
            with pytest.raises(WorkerUnavailable, match="expired"):
                cli.submit_range([True], [None], deadline_s=5.0)
        finally:
            cli.close()
        assert _count("rpc_deadline_expired_total") == 1
        # shed before entering the service, so never counted as accepted
        assert _count("rpc_requests_total", kind="range") == 0
        _assert_server_alive(h.address)


def test_credit_backpressure_stalls_then_sheds():
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(conn_credits=2)) as h:
        cli = _client(h.address, credit_wait_s=0.3)
        try:
            # 5 rows can never fit a 2-credit grant: the client stalls
            # on credits (counted) and sheds as transient backpressure
            with pytest.raises(WorkerUnavailable, match="backpressure"):
                cli.submit_range([True] * 5, [None] * 5)
            assert _count("rpc_credit_waits_total") >= 1
            # a batch within budget still flows, and the RESULT's
            # replenish restores the grant for the next one
            for _ in range(3):
                out = cli.submit_range([True, True], [None, None])
                assert out.tolist() == [True, True]
        finally:
            cli.close()


def test_hedged_interactive_send_first_reply_wins():
    GLOBAL.reset()
    with _Harness(serve_cfg=ServeConfig(buckets=(8,), max_wait_s=0.05)) as h:
        cli = _client(h.address, hedge_after_s=0.0)
        try:
            out = cli.submit_range([True, False], [None, None],
                                   lane=LANE_INTERACTIVE)
            assert out.tolist() == [True, False]
        finally:
            cli.close()
        assert _count("rpc_hedges_total") >= 1


# ------------------------------------------------------- frame adversity
@pytest.mark.parametrize("kind,frame_bytes", [
    ("bad_magic", b"\x00" * 12),
    ("oversize", _HEADER.pack(MAGIC, HELLO, 0, 2**31 - 1, 0)),
    ("checksum", _HEADER.pack(MAGIC, HELLO, 0, 4, 0xDEAD) + b"ruin"),
    ("decode", _HEADER.pack(MAGIC, HELLO, 0, 4,
                            zlib.crc32(b"ruin")) + b"ruin"),
    ("torn", _HEADER.pack(MAGIC, HELLO, 0, 64, 0)[:6]),
])
def test_poisoned_hello_is_counted_not_fatal(kind, frame_bytes):
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(hello_timeout_s=1.0)) as h:
        sock = _raw_conn(h.address)
        try:
            sock.sendall(frame_bytes)
        finally:
            sock.close()  # "torn" needs the close; harmless for the rest
        _await_count("rpc_frame_errors_total", kind=kind)
        _assert_server_alive(h.address)
        assert h.server.frames_clean


def test_first_frame_must_be_hello():
    GLOBAL.reset()
    with _Harness() as h:
        sock = _raw_conn(h.address)
        try:
            send_frame_sock(sock, PING, {"t": time.time()})
            _await_count("rpc_frame_errors_total", kind="protocol")
        finally:
            sock.close()
        _assert_server_alive(h.address)


def test_midframe_disconnect_after_handshake():
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(frame_timeout_s=1.0)) as h:
        sock = _handshake(h.address)
        # half a SUBMIT frame, then vanish
        sock.sendall(_HEADER.pack(MAGIC, 3, 0, 128, 0) + b"x" * 40)
        sock.close()
        _await_count("rpc_frame_errors_total", kind="torn")
        _assert_server_alive(h.address)


def test_slow_loris_frame_hits_deadline_not_a_hang():
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(frame_timeout_s=0.4,
                                    idle_tick_s=0.1)) as h:
        sock = _handshake(h.address)
        try:
            # declare a 100B payload, trickle 10B, stall past the
            # frame deadline: the server must fail it as slow_frame
            # within frame_timeout_s, not park in recv forever
            sock.sendall(_HEADER.pack(MAGIC, 3, 0, 100, 0) + b"y" * 10)
            _await_count("rpc_frame_errors_total", kind="slow_frame",
                         timeout=5.0)
        finally:
            sock.close()
        _assert_server_alive(h.address)


# ----------------------------------------------------- drain and restart
def test_draining_stop_under_load_closes_no_frame_midwrite():
    GLOBAL.reset()
    with _Harness(serve_cfg=ServeConfig(buckets=(8,), max_wait_s=0.05)) as h:
        cli = _client(h.address)
        results, sheds = [], []

        def _caller():
            try:
                results.append(
                    cli.submit_range([True] * 8, [None] * 8).tolist())
            except WorkerUnavailable as exc:
                sheds.append(exc)

        threads = [threading.Thread(target=_caller) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.02)  # let submits get in flight
            h.stop_server()
            for t in threads:
                t.join(timeout=30.0)
            assert not any(t.is_alive() for t in threads)
            # every call resolved: served before the drain, or shed as
            # transient (goaway) for the caller's ladder to retry
            assert len(results) + len(sheds) == 4
            for verdicts in results:
                assert verdicts == [True] * 8
            # THE invariant: nothing was cut mid-frame by the drain
            assert h.server.frames_clean
            assert _count("rpc_frame_errors_total", kind="midframe_close") \
                == 0
            assert _count("rpc_goaways_total", role="server") >= 1
            # the listener is gone now, so a fresh call exhausts the
            # redial ladder into WorkerUnavailable — never a hang
            with pytest.raises(WorkerUnavailable):
                cli.submit_range([True], [None])
        finally:
            cli.close()


def test_client_reconnects_after_server_restart_on_same_port():
    GLOBAL.reset()
    first = _Harness()
    host, port = first.address
    cli = _client((host, port), redial_attempts=6, redial_cap_s=0.2)
    try:
        assert cli.submit_range([True], [None]).tolist() == [True]
        first.stop()
        with pytest.raises(WorkerUnavailable):
            cli.submit_range([True], [None])
        with _Harness(rpc_cfg=RpcConfig(port=port)) as second:
            assert second.address[1] == port
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    out = cli.submit_range([True, False], [None, None])
                    break
                except WorkerUnavailable:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.05)
            assert out.tolist() == [True, False]
    finally:
        cli.close()
    assert _count("rpc_redials_total", outcome="ok") >= 2
    assert _count("rpc_redials_total", outcome="error") >= 1


# ------------------------------------------------- columnar batch ingest
def _batch_payload(truth=(True, False), req_id_base=7):
    return encode_submit_batch(
        fmt=FMT_OPAQUE, lane=LANE_BULK, req_id_base=req_id_base,
        deadline=time.time() + 30.0, proof_cells=opaque_cells(truth))


def test_columnar_codec_roundtrip_zero_pickle_zero_copy(monkeypatch):
    """The codec contract behind the tentpole: N rows decode into
    read-only numpy views over the payload buffer with zero pickle
    calls and zero per-row Python objects until materialization."""
    calls = {"n": 0}
    real_loads = pickle.loads

    def _counting_loads(*a, **kw):
        calls["n"] += 1
        return real_loads(*a, **kw)

    monkeypatch.setattr(pickle, "loads", _counting_loads)

    n = 256
    truth = [i % 3 != 0 for i in range(n)]
    bits = [32 + (i % 3) * 16 for i in range(n)]
    flags = [0 if t else 1 for t in truth]
    offs = [1000 * i for i in range(n)]
    payload = encode_submit_batch(
        fmt=FMT_OPAQUE, lane=LANE_BULK, req_id_base=1 << 40,
        deadline=1.5e9, proof_cells=opaque_cells(truth),
        bits=bits, flags=flags, deadline_off_us=offs)
    batch = decode_submit_batch(payload)

    assert calls["n"] == 0, "columnar decode must never unpickle"
    assert (batch.n_rows, batch.lane, batch.fmt_name) == (n, LANE_BULK,
                                                          "opaque")
    assert batch.req_id_base == 1 << 40
    for arr in (batch.bits, batch.flags, batch.deadline_off_us,
                batch.proof_len, batch.com_len, batch.proof_planes,
                batch.com_planes):
        # views over the frame bytes, not per-row copies
        assert arr.flags.owndata is False
        assert arr.flags.writeable is False
    assert batch.bits.tolist() == bits
    assert batch.flags.tolist() == flags
    assert batch.deadline_off_us.tolist() == offs
    assert np.allclose(batch.deadline_offsets_s, np.asarray(offs) * 1e-6)

    proofs, coms = materialize_rows(batch)
    assert calls["n"] == 0
    assert proofs == truth and coms == [None] * n


def test_columnar_codec_fuzz_ragged_shapes():
    """Seeded fuzz over ragged cell shapes: exact round-trip, and any
    one-byte truncation/extension is rejected, never mis-decoded."""
    rng = random.Random(0xC01A)
    for _ in range(40):
        n = rng.randrange(1, 33)
        proof_cells = [bytes(rng.randrange(256)
                             for _ in range(rng.randrange(0, 49)))
                       for _ in range(n)]
        com_cells = None if rng.random() < 0.4 else \
            [bytes(rng.randrange(256)
                   for _ in range(rng.randrange(0, 25)))
             for _ in range(n)]
        bits = [rng.randrange(1 << 16) for _ in range(n)]
        offs = [rng.randrange(1 << 20) for _ in range(n)]
        payload = encode_submit_batch(
            fmt=FMT_OPAQUE, lane=LANE_BULK,
            req_id_base=rng.randrange(1 << 48), deadline=1.5e9,
            proof_cells=proof_cells, com_cells=com_cells, bits=bits,
            deadline_off_us=offs)
        batch = decode_submit_batch(payload)
        assert batch.n_rows == n
        assert batch.bits.tolist() == bits
        assert batch.deadline_off_us.tolist() == offs
        for i in range(n):
            assert batch.proof_cell(i) == proof_cells[i]
            if com_cells is not None:
                assert batch.com_cell(i) == com_cells[i]
        with pytest.raises(ColumnarError):
            decode_submit_batch(payload[:-1])
        with pytest.raises(ColumnarError):
            decode_submit_batch(payload + b"\x00")


def test_columnar_batch_end_to_end():
    """One SUBMIT_BATCH frame in, one RESULT out: per-row verdicts
    intact, ONE rpc_requests_total bump for the whole frame, batch
    families counted on both roles, no frame errors."""
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="col")
        try:
            truth = [True, False, True, True, False]
            out = cli.submit_range_batch(truth, [None] * 5)
            assert isinstance(out, np.ndarray) and out.dtype == bool
            assert out.tolist() == truth
            assert cli.server_version == 4
            assert cli.server_batch is True
            assert cli.server_trace is True
        finally:
            cli.close()
        for role in ("client", "server"):
            assert _count("rpc_batch_frames_total", role=role,
                          tms="col") == 1
            assert _count("rpc_batch_rows_total", role=role,
                          tms="col") == 5
            assert _count("rpc_batch_bytes_total", role=role,
                          tms="col") > 0
        # the whole frame is ONE request-accounting event, not five
        assert _count("rpc_requests_total", tms="col", kind="range") == 1
        assert _count("rpc_decode_seconds", fmt="columnar") == 1
        # rows fanned into the scheduler under the connection's tenant
        assert _count("serve_tenant_drains_total", tms_id="col") == 5
        assert _count("rpc_frame_errors_total") == 0
        assert h.server.frames_clean


def test_prefer_batch_routes_submits_through_frames():
    """``prefer_batch=True`` + a batch-capable server: the plain
    ``submit_range`` duck-type path rides columnar frames with no
    caller-side change."""
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="auto", prefer_batch=True)
        try:
            out = cli.submit_range([True, False], [None, None])
            assert out.tolist() == [True, False]
        finally:
            cli.close()
        assert _count("rpc_batch_frames_total", role="client",
                      tms="auto") == 1
        assert _count("rpc_batch_frames_total", role="server",
                      tms="auto") == 1


def _flip_last_byte(frame: bytes) -> bytes:
    ruined = bytearray(frame)
    ruined[-1] ^= 0xFF
    return bytes(ruined)


def _tamper_row_count(payload: bytes, n: int = 9) -> bytes:
    # n_rows is the u32 at offset 4 of the "<HBBIQdII" batch header
    return payload[:4] + struct.pack("<I", n) + payload[8:]


@pytest.mark.parametrize("kind,build", [
    # sub-header payload: can't even read the batch header
    ("decode", lambda p: encode_raw_frame(SUBMIT_BATCH, p[:16])),
    # garbage header: wrong columnar version / fmt / lane
    ("decode", lambda p: encode_raw_frame(SUBMIT_BATCH, b"\xff" * 64)),
    # declared shape disagrees with the actual byte count, both ways
    ("row_count", lambda p: encode_raw_frame(SUBMIT_BATCH,
                                             p + b"\x00" * 4)),
    ("row_count", lambda p: encode_raw_frame(SUBMIT_BATCH,
                                             _tamper_row_count(p))),
    # frame-level adversity still applies to raw payloads
    ("checksum", lambda p: _flip_last_byte(
        encode_raw_frame(SUBMIT_BATCH, p))),
    ("torn", lambda p: encode_raw_frame(SUBMIT_BATCH, p)[:-5]),
])
def test_poisoned_batch_frame_is_counted_not_fatal(kind, build):
    GLOBAL.reset()
    with _Harness(rpc_cfg=RpcConfig(frame_timeout_s=1.0)) as h:
        sock = _handshake(h.address)
        try:
            sock.sendall(build(_batch_payload()))
        finally:
            sock.close()  # "torn" needs the close; harmless for the rest
        _await_count("rpc_frame_errors_total", kind=kind)
        _assert_server_alive(h.address)


def test_batch_submit_buffer_coalesces_single_row_adds():
    """Row-at-a-time callers ride batch frames: max_rows trips one
    flush for a burst, the delay timer ships a straggler, and close()
    drains what's left."""
    GLOBAL.reset()
    with _Harness() as h:
        cli = _client(h.address, tms_id="buf")
        buf = BatchSubmitBuffer(cli, max_rows=4, max_delay_s=5.0)
        try:
            truth = [True, False, True, True]
            futs = [buf.add(t) for t in truth]
            assert [f.result(timeout=10.0) for f in futs] == truth
            assert _count("rpc_batch_frames_total", role="client",
                          tms="buf") == 1
            assert _count("rpc_batch_rows_total", role="client",
                          tms="buf") == 4

            # a lone row must not wait for max_rows: the delay timer
            # fires the flush
            quick = BatchSubmitBuffer(cli, max_rows=100,
                                      max_delay_s=0.02)
            try:
                assert quick.add(False).result(timeout=10.0) is False
            finally:
                quick.close()
            assert _count("rpc_batch_rows_total", role="client",
                          tms="buf") == 5

            # close() drains the tail
            tail = buf.add(True)
            buf.close()
            assert tail.result(timeout=10.0) is True
            with pytest.raises(RuntimeError):
                buf.add(True)
        finally:
            buf.close()
            cli.close()
        assert _count("rpc_frame_errors_total") == 0
        assert h.server.frames_clean
