"""ManagementService facade completeness: vault, wallet manager, selector,
sig service, pp manager, request factory (reference token/tms.go:32-185,
sdk/vault/vault.go)."""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.core.registry import TMSID, TMSProvider, \
    default_registry
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus
from fabric_token_sdk_tpu.token.tms import TokenManagementService, Vault


@pytest.fixture
def node():
    keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [keys.identity]
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    bus = SessionBus()
    issuer = TokenNode("issuer", keys, bus, cc)
    alice = TokenNode("alice", new_signing_identity(), bus, cc)
    ev = alice.execute(alice.issue("issuer", "alice", "USD", hex(100)))
    assert ev.status == "VALID"
    return alice


def test_node_bound_tms_surface(node):
    tms = node.management_service()
    assert tms.label == "fabtoken"
    # vault QueryEngine reflects the node's token store
    vault = tms.vault()
    assert vault.balance("alice", "USD") == 100
    toks = vault.unspent_tokens("alice")
    assert list(vault.unspent_tokens_iterator("alice")) == toks
    assert vault.is_mine(toks[0].id, "alice")
    assert vault.get_status("missing") == "Unknown"
    # wallet manager is the node's registry; selector is the node's
    assert tms.wallet_manager() is node.wallets
    assert tms.selector_manager() is node.selector
    assert tms.sig_service() is node.keys
    # pp manager reads the ledger-derived public parameters
    assert tms.public_parameters_manager().precision() == 64
    assert tms.public_parameters_manager().issuers()


def test_tms_request_roundtrip(node):
    tms = node.management_service()
    # a real committed request re-derives its wire bytes AND actions
    raw = node.ttxdb.get_token_request(
        node.tokendb.unspent_tokens("alice")[0].id.tx_id)
    restored = tms.new_full_request_from_bytes(raw)
    assert restored.to_bytes() == raw
    outs = restored.outputs()
    assert len(outs) == 1  # the single issue output
    # caching: one facade per TMSID, so bind() state persists
    assert node.management_service() is node.management_service()


def test_unbound_components_raise():
    reg = default_registry()
    provider = TMSProvider(reg)
    pp = fabtoken.setup(64)
    tmsid = TMSID("n1", "c1", "ns1")
    provider.store_public_params(tmsid, pp.serialize())
    tms = provider.get_management_service(tmsid)
    assert isinstance(tms, TokenManagementService)
    with pytest.raises(LookupError):
        tms.vault()
    with pytest.raises(LookupError):
        tms.wallet_manager()
    # binding attaches node-scoped parts
    from fabric_token_sdk_tpu.services.db.sqldb import TokenDB

    tms.bind(vault=Vault(TokenDB(":memory:")))
    assert tms.vault().balance("w", "USD") == 0


def test_vault_certification_storage():
    from fabric_token_sdk_tpu.services.db.sqldb import CertificationDB, \
        TokenDB
    from fabric_token_sdk_tpu.token.model import ID

    v = Vault(TokenDB(":memory:"), certification_db=CertificationDB())
    assert not v.certification_exists(ID("t", 0))
    v.store_certifications({ID("t", 0): b"c"})
    assert v.certification_exists(ID("t", 0))


def test_request_bind_to(node):
    """request.go:1069 BindTo: foreign sender/receiver identities bind to
    the submitter's identity; locally-owned ones are skipped."""
    from fabric_token_sdk_tpu.core.fabtoken.driver import OutputSpec
    from fabric_token_sdk_tpu.token.request_builder import Request

    bob = TokenNode("bob", new_signing_identity(), node.bus, node.cc)
    sel = node.selector.select("alice", "USD", hex(30), "tx-bind")
    bob_owner, bob_ai = bob.recipient_identity()
    req = Request("tx-bind", node.driver)
    req.transfer(
        sel.tokens,
        [OutputSpec(owner=bob_owner, token_type="USD", value=30,
                    audit_info=bob_ai),
         OutputSpec(owner=node.owner_wallet.recipient_identity()[0],
                    token_type="USD", value=70,
                    audit_info=node.owner_wallet.recipient_identity()[1])],
        wallet=node.token_loader,
        sender_audit_info=node.owner_wallet.audit_info_for,
        receivers=["bob", "alice"])

    calls = []

    class Binder:
        def bind(self, long_term, ephemeral):
            calls.append((bytes(long_term), bytes(ephemeral)))

    req.bind_to(Binder(), b"submitter-id", wallet_service=node.wallets)
    bound = {eph for _, eph in calls}
    # bob's receiver identity is foreign -> bound
    assert bytes(bob_owner) in bound
    # every bound pair targets the submitter identity
    assert all(lt == b"submitter-id" for lt, _ in calls)
    # alice's own sender identities are skipped
    for sender in req.input_owner_ids():
        assert bytes(sender) not in bound
