"""Fake DB-API-2 postgres driver for exercising the pgdb dialect layer.

Plays the role testcontainers-postgres plays in the reference's db tests
when no server is available: accepts the POSTGRES dialect pgdb emits
(%s placeholders, ON CONFLICT upserts, BYTEA/BIGSERIAL DDL) and executes
it on sqlite, whose ON CONFLICT (pk) DO UPDATE SET ... EXCLUDED semantics
match postgres. What this validates: pgdb's query/DDL translation produces
well-formed postgres SQL with correct upsert column handling — not
postgres server behavior itself (the real-driver path is the same code
with psycopg2 injected).
"""

from __future__ import annotations

import re
import sqlite3

IntegrityError = sqlite3.IntegrityError


def _to_sqlite_ddl(stmt: str) -> str:
    s = stmt.replace("BIGSERIAL PRIMARY KEY",
                     "INTEGER PRIMARY KEY AUTOINCREMENT")
    s = s.replace("''::bytea", "x''")
    s = s.replace("BYTEA", "BLOB")
    s = s.replace("DOUBLE PRECISION", "REAL")
    s = re.sub(r"\bBIGINT\b", "INTEGER", s)
    return s


class _Cursor:
    def __init__(self, conn: sqlite3.Connection):
        self._conn = conn
        self._cur = None

    @property
    def rowcount(self):
        return self._cur.rowcount if self._cur is not None else -1

    def execute(self, sql: str, params=()):
        self._cur = self._conn.execute(_to_sqlite_ddl(sql.replace("%s", "?")),
                                       params)

    def executemany(self, sql: str, seq):
        self._cur = self._conn.executemany(sql.replace("%s", "?"), seq)

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()


class _Connection:
    def __init__(self):
        self._conn = sqlite3.connect(":memory:", check_same_thread=False)

    def cursor(self):
        return _Cursor(self._conn)

    def commit(self):
        self._conn.commit()

    def rollback(self):
        self._conn.rollback()

    def close(self):
        self._conn.close()


def connect(dsn: str) -> _Connection:
    return _Connection()
