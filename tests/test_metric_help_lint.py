"""Tier-1 wrapper around scripts/check_metric_help.py: every stable
metric family registered anywhere in the tree must carry HELP text
(inline, via describe(), or through a hoisted family-metadata dict).

The standalone script is the pre-commit entry point; this test makes
the invariant part of the suite so a new registration site without HELP
fails CI, not just the linter nobody ran.
"""

import importlib.util
import pathlib

_SCRIPT = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
           / "check_metric_help.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_metric_help",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_stable_family_registration_has_help():
    mod = _load()
    offenders = mod.find_offenders()
    assert not offenders, (
        "stable families registered without HELP text (add the family to "
        "the module's hoisted metadata dict + describe() loop, or pass "
        f"help= at the call site): {offenders}")


def test_linter_sees_the_stable_inventory():
    """Guard the guard: the linter must actually be scanning a non-trivial
    inventory and file set, or an import/path regression would turn it
    into a silent no-op."""
    mod = _load()
    assert len(mod._stable_families()) > 50
    files = mod._source_files()
    assert any(f.name == "bench.py" for f in files)
    assert sum(1 for f in files if f.suffix == ".py") > 50
