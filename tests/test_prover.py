"""Host-prover edge cases + prover API contracts (cheap, CPU tier-1).

These pin the HOST prover semantics the device path must match (the
byte-for-byte match itself is tests/test_prover_parity.py): the range
edges value=0 and value=2^n - 1 prove and verify, an out-of-range
witness silently truncates into an invalid proof, pinned
``RangeProverDraws`` / ``TypeAndSumDraws`` make proofs deterministic,
and the ``DeviceRangeProver`` prove-time contract (out-of-range raises
unless forge=True) fires before any device work. Everything here runs
at 4 bits with no device compiles.
"""

import random

import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.crypto import transfer_proof as tp
from fabric_token_sdk_tpu.crypto import token_commit
from fabric_token_sdk_tpu.harness.corpus import ProofCorpus, _seeded_draws
from fabric_token_sdk_tpu.models import witness_pack
from fabric_token_sdk_tpu.prover import DeviceRangeProver

N_BITS = 4


@pytest.fixture(scope="module")
def pp():
    return setup.setup(N_BITS)


def _prove(pp, value, bf, draws=None):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length, draws=draws)
    return proof, com


def _verify_ok(pp, proof, com) -> bool:
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    try:
        rp.range_verify(proof, com, cg, rpp.left_generators,
                        rpp.right_generators, rpp.P, rpp.Q,
                        rpp.number_of_rounds, rpp.bit_length)
        return True
    except rp.ProofError:
        return False


# ---------------------------------------------------------- host edges


@pytest.mark.parametrize("value", [0, (1 << N_BITS) - 1, 5])
def test_host_edge_values_prove_and_verify(pp, value):
    proof, com = _prove(pp, value, bn254.fr_rand())
    assert _verify_ok(pp, proof, com)


def test_host_out_of_range_witness_truncates_to_invalid_proof(pp):
    # the host prover decomposes only the low n bits but commits the
    # full value: the proof comes out syntactically fine and MUST fail
    # verification (this is the forged-corpus mechanism)
    proof, com = _prove(pp, 1 << N_BITS, bn254.fr_rand())
    assert not _verify_ok(pp, proof, com)


def test_host_draws_pin_proof_bytes(pp):
    d = _seeded_draws(random.Random(5), N_BITS)
    bf = 1234567
    p1, c1 = _prove(pp, 9, bf, draws=d)
    p2, c2 = _prove(pp, 9, bf, draws=d)
    assert c1 == c2
    assert p1.serialize() == p2.serialize()
    # different draws -> different transcript
    p3, _ = _prove(pp, 9, bf, draws=_seeded_draws(random.Random(6), N_BITS))
    assert p3.serialize() != p1.serialize()


# ------------------------------------------- device prove-time contract


def test_device_prover_rejects_out_of_range_at_prove_time(pp):
    prover = DeviceRangeProver(pp)
    with pytest.raises(ValueError, match="out of range"):
        prover.prove([1 << N_BITS], [bn254.fr_rand()])
    with pytest.raises(ValueError, match="out of range"):
        prover.prove([-1], [bn254.fr_rand()])
    # lazy params: the contract fires before any table build
    assert prover._params is None


def test_device_prover_rejects_shape_mismatches(pp):
    prover = DeviceRangeProver(pp)
    with pytest.raises(ValueError, match="blinding factors"):
        prover.prove([1, 2], [bn254.fr_rand()])
    with pytest.raises(ValueError, match="draws"):
        prover.prove([1], [bn254.fr_rand()],
                     draws=[rp.RangeProverDraws.random(N_BITS)] * 2)
    assert prover._params is None


def test_witness_pack_roundtrip_validation():
    d = rp.RangeProverDraws.random(N_BITS)
    packed = witness_pack.pack_range_witnesses([3], [7], [d], N_BITS)
    assert packed.shape == (1, witness_pack.witness_width(N_BITS))
    padded = witness_pack.pad_witness_rows(packed, 4)
    assert padded.shape[0] == 4 and (padded[1:] == 0).all()
    with pytest.raises(ValueError, match="draws row"):
        witness_pack.pack_range_witnesses(
            [3], [7], [rp.RangeProverDraws.random(N_BITS * 2)], N_BITS)


# -------------------------------------------------- type-and-sum seam


def test_type_and_sum_draws_pin_proof_bytes(pp):
    ped = pp.pedersen_generators
    type_zr = bn254.hash_to_zr(b"USD")
    type_bf = bn254.fr_rand()
    ct = bn254.g1_add(bn254.g1_mul(ped[0], type_zr),
                      bn254.g1_mul(ped[2], type_bf))
    in_bfs = [bn254.fr_rand(), bn254.fr_rand()]
    out_bfs = [bn254.fr_rand(), bn254.fr_rand()]
    inputs = [token_commit.commit_token("USD", 5, bf, ped) for bf in in_bfs]
    outputs = [token_commit.commit_token("USD", 5, bf, ped) for bf in out_bfs]
    d = tp.TypeAndSumDraws(
        r_type=11, r_type_bf=22, r_in_values=[33, 44],
        r_in_bfs=[55, 66], r_sum_bf=77)
    args = (ped, inputs, outputs, ct, [5, 5], in_bfs, out_bfs,
            type_zr, type_bf)
    p1 = tp.type_and_sum_prove(*args, draws=d)
    p2 = tp.type_and_sum_prove(*args, draws=d)
    assert p1.serialize() == p2.serialize()
    assert tp.type_and_sum_prove(*args).serialize() != p1.serialize()


# ------------------------------------------------------- ProofCorpus


def test_corpus_host_source_values_forgeries_and_provenance(pp):
    corpus = ProofCorpus(pp, source="host", seed=23, forge_every=3)
    entries = corpus.generate(7)
    assert [e.forged for e in entries] == [
        False, False, True, False, False, True, False]
    assert entries[0].value == 0
    assert entries[1].value == (1 << N_BITS) - 1
    for e in entries:
        if e.forged:
            assert e.value >= (1 << N_BITS)
        assert _verify_ok(pp, e.proof, e.commitment) == (not e.forged)
    prov = corpus.provenance()
    assert prov["source"] == "host" and prov["seed"] == 23
    assert prov["forge_every"] == 3 and prov["bits"] == N_BITS
    assert prov["edge_values"] == [0, (1 << N_BITS) - 1]


def test_corpus_is_seed_deterministic(pp):
    a = ProofCorpus(pp, source="host", seed=9).generate(3)
    b = ProofCorpus(pp, source="host", seed=9).generate(3)
    assert all(x.proof.serialize() == y.proof.serialize()
               for x, y in zip(a, b))
    c = ProofCorpus(pp, source="host", seed=10).generate(3)
    assert a[2].proof.serialize() != c[2].proof.serialize()


def test_corpus_arrival_schedule_and_source_validation(pp):
    corpus = ProofCorpus(pp, source="host", seed=1)
    sched = corpus.arrival_schedule(50, rate_hz=1000.0)
    assert len(sched) == 50
    assert sched == sorted(sched) and sched[0] >= 0.0
    with pytest.raises(ValueError, match="source"):
        ProofCorpus(pp, source="tpu")
