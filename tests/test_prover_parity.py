"""Device-prover parity bar: byte-identical to the host provers.

The tentpole correctness contract: every proof the device prover
synthesizes must be accepted BIT-IDENTICALLY by both verifier paths —
``serialize()`` equals the host prover's output under the same
``RangeProverDraws`` / ``TypeAndSumDraws``, the pure-host verifier
accepts it, the TPU batch verifier accepts it, and seeded FORGED
(out-of-range) witness rows produce the same bytes on both paths and
are rejected by both verifiers.

Runs at 16 bits on the CPU backend (tier-1; conftest isolates this
module in its own process — it compiles the fused prove chunk program
AND the batch-verifier passes). The 32-bit sweep is @slow.
"""

import random

import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup
from fabric_token_sdk_tpu.crypto import transfer_proof as tp
from fabric_token_sdk_tpu.crypto import token_commit
from fabric_token_sdk_tpu.harness.corpus import _seeded_draws
from fabric_token_sdk_tpu.models.range_verifier import BatchRangeVerifier
from fabric_token_sdk_tpu.prover import (DeviceRangeProver,
                                         DeviceTransferProver)

N_BITS = 16
# 4-row chunks: big enough to exercise padding + multi-row batching,
# small enough that the fused chunk program compiles on the CPU backend
# (the 32-row size class is known to crash jaxlib's XLA:CPU here).
CHUNK = 4


@pytest.fixture(scope="module")
def pp():
    return setup.setup(N_BITS)


def _host_prove(pp, value, bf, draws):
    rpp = pp.range_proof_params
    cg = pp.pedersen_generators[1:3]
    com = bn254.g1_add(bn254.g1_mul(cg[0], value), bn254.g1_mul(cg[1], bf))
    proof = rp.range_prove(com, value, cg, bf, rpp.left_generators,
                           rpp.right_generators, rpp.P, rpp.Q,
                           rpp.number_of_rounds, rpp.bit_length,
                           draws=draws)
    return proof, com


def _host_accepts(pp, proof, com) -> bool:
    rpp = pp.range_proof_params
    try:
        rp.range_verify(proof, com, pp.pedersen_generators[1:3],
                        rpp.left_generators, rpp.right_generators,
                        rpp.P, rpp.Q, rpp.number_of_rounds,
                        rpp.bit_length)
        return True
    except rp.ProofError:
        return False


def test_device_range_proofs_bit_identical_and_verified_both_paths(pp):
    rng = random.Random(41)
    # edges + a mid value, then one FORGED out-of-range row
    values = [0, (1 << N_BITS) - 1, rng.randrange(1 << N_BITS)]
    forged_value = (1 << N_BITS) + 7
    bfs = [rng.randrange(1, bn254.R) for _ in range(4)]
    draws = [_seeded_draws(rng, N_BITS) for _ in range(4)]

    prover = DeviceRangeProver(pp, chunk_rows=CHUNK)
    dev_proofs, dev_coms = prover.prove(values, bfs[:3], draws=draws[:3])
    forged_proofs, forged_coms = prover.prove(
        [forged_value], bfs[3:], draws=draws[3:], forge=True)

    all_proofs = dev_proofs + forged_proofs
    all_coms = dev_coms + forged_coms
    all_values = values + [forged_value]

    # byte parity: device serialize() == host serialize(), same draws
    for i, v in enumerate(all_values):
        host_proof, host_com = _host_prove(pp, v, bfs[i], draws[i])
        assert all_coms[i] == host_com, f"commitment mismatch row {i}"
        assert all_proofs[i].serialize() == host_proof.serialize(), \
            f"proof bytes diverge from host prover at row {i}"

    # host verifier path: valid rows accept, the forged row rejects
    verdicts = [_host_accepts(pp, p, c)
                for p, c in zip(all_proofs, all_coms)]
    assert verdicts == [True, True, True, False]

    # TPU batch verifier path: same verdict vector, bit for bit
    batch = BatchRangeVerifier(pp)
    out = batch.verify(all_proofs, all_coms)
    assert out.tolist() == [True, True, True, False]


def test_type_and_sum_device_matches_host(pp):
    ped = pp.pedersen_generators
    rng = random.Random(43)
    type_zr = bn254.hash_to_zr(b"USD")
    statements, host_args, draws = [], [], []
    for k in range(2):                    # B=2: batching parity too
        type_bf = rng.randrange(1, bn254.R)
        ct = bn254.g1_add(bn254.g1_mul(ped[0], type_zr),
                          bn254.g1_mul(ped[2], type_bf))
        in_bfs = [rng.randrange(1, bn254.R) for _ in range(2)]
        out_bfs = [rng.randrange(1, bn254.R) for _ in range(2)]
        vals = [10 + k, 20 + k]
        inputs = [token_commit.commit_token("USD", v, bf, ped)
                  for v, bf in zip(vals, in_bfs)]
        outputs = [token_commit.commit_token("USD", v, bf, ped)
                   for v, bf in zip(vals, out_bfs)]
        d = tp.TypeAndSumDraws(
            r_type=rng.randrange(1, bn254.R),
            r_type_bf=rng.randrange(1, bn254.R),
            r_in_values=[rng.randrange(1, bn254.R) for _ in range(2)],
            r_in_bfs=[rng.randrange(1, bn254.R) for _ in range(2)],
            r_sum_bf=rng.randrange(1, bn254.R))
        statements.append({
            "inputs": inputs, "outputs": outputs,
            "commitment_to_type": ct, "in_values": vals,
            "in_bfs": in_bfs, "out_bfs": out_bfs,
            "type_zr": type_zr, "type_bf": type_bf})
        host_args.append((ped, inputs, outputs, ct, vals, in_bfs,
                          out_bfs, type_zr, type_bf))
        draws.append(d)

    dev = DeviceTransferProver(pp).prove_type_and_sum(statements,
                                                      draws=draws)
    for k in range(2):
        host = tp.type_and_sum_prove(*host_args[k], draws=draws[k])
        assert dev[k].serialize() == host.serialize(), \
            f"type-and-sum bytes diverge from host at row {k}"
        # host verifier accepts the device proof
        tp.type_and_sum_verify(dev[k], ped, statements[k]["inputs"],
                               statements[k]["outputs"])
        # a tampered response must reject
        bad = tp.TypeAndSumProof(
            commitment_to_type=dev[k].commitment_to_type,
            input_blinding_factors=dev[k].input_blinding_factors,
            input_values=dev[k].input_values,
            type_=(dev[k].type_ + 1) % bn254.R,
            type_blinding_factor=dev[k].type_blinding_factor,
            equality_of_sum=dev[k].equality_of_sum,
            challenge=dev[k].challenge)
        with pytest.raises(tp.ProofError):
            tp.type_and_sum_verify(bad, ped, statements[k]["inputs"],
                                   statements[k]["outputs"])


@pytest.mark.slow
def test_device_transfer_prove_matches_host_end_to_end(pp):
    """Full composition (Σ + output range proofs): the serialized
    TransferProof from the device twin equals the host's byte for byte,
    and the host transfer_verify accepts it."""
    ped = pp.pedersen_generators
    rng = random.Random(47)
    in_bfs = [rng.randrange(1, bn254.R) for _ in range(2)]
    out_bfs = [rng.randrange(1, bn254.R) for _ in range(2)]
    iw = [("USD", 30, in_bfs[0]), ("USD", 12, in_bfs[1])]
    ow = [("USD", 25, out_bfs[0]), ("USD", 17, out_bfs[1])]
    inputs = [token_commit.commit_token(t, v, bf, ped) for t, v, bf in iw]
    outputs = [token_commit.commit_token(t, v, bf, ped) for t, v, bf in ow]
    draws = tp.TransferDraws(
        type_bf=rng.randrange(1, bn254.R),
        ts=tp.TypeAndSumDraws(
            r_type=rng.randrange(1, bn254.R),
            r_type_bf=rng.randrange(1, bn254.R),
            r_in_values=[rng.randrange(1, bn254.R) for _ in range(2)],
            r_in_bfs=[rng.randrange(1, bn254.R) for _ in range(2)],
            r_sum_bf=rng.randrange(1, bn254.R)),
        ranges=[_seeded_draws(rng, N_BITS) for _ in range(2)])

    dev_raw = DeviceTransferProver(pp, range_chunk_rows=CHUNK) \
        .transfer_prove(iw, ow, inputs, outputs, draws=draws)
    host_raw = tp.transfer_prove(iw, ow, inputs, outputs, pp, draws=draws)
    assert dev_raw == host_raw, "serialized TransferProof diverges"
    tp.transfer_verify(dev_raw, inputs, outputs, pp)


@pytest.mark.slow
def test_device_range_parity_32bit():
    pp = setup.setup(32)
    rng = random.Random(53)
    values = [0, (1 << 32) - 1]
    bfs = [rng.randrange(1, bn254.R) for _ in values]
    draws = [_seeded_draws(rng, 32) for _ in values]
    prover = DeviceRangeProver(pp, chunk_rows=2)
    dev_proofs, dev_coms = prover.prove(values, bfs, draws=draws)
    for i, v in enumerate(values):
        host_proof, host_com = _host_prove(pp, v, bfs[i], draws[i])
        assert dev_coms[i] == host_com
        assert dev_proofs[i].serialize() == host_proof.serialize()
        assert _host_accepts(pp, dev_proofs[i], dev_coms[i])
