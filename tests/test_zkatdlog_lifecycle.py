"""zkatdlog driver through the FULL services tier (VERDICT round-1 #6).

The same issue -> transfer -> redeem choreography as test_ttx_lifecycle, but
with commitment tokens: wallet openings distributed over sessions, selector
over deobfuscated balances, ZK proofs behind the validator, and the auditor
running the batched commitment-reopen check on every request.
"""

import pytest

from fabric_token_sdk_tpu.core import zkatdlog
from fabric_token_sdk_tpu.core.zkatdlog.driver import ZkDlogDriverService
from fabric_token_sdk_tpu.crypto import setup
from fabric_token_sdk_tpu.services.auditor import AuditError, AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def pp_module():
    return setup.setup(BIT_LENGTH)


@pytest.fixture
def net(pp_module):
    pp = pp_module
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    # device=False: this suite exercises the SERVICES integration (wallet
    # openings, selector, distribution, auditor flow); the device kernels
    # themselves are covered by test_zkatdlog_e2e / test_zk_audit /
    # test_range_verifier — compiling them again here would dominate the
    # suite's runtime on the CPU backend for zero extra coverage.
    validator = zkatdlog.new_validator(pp, Deserializer(), device=False)
    ledger = MemoryLedger()
    cc = TokenChaincode(validator, ledger, pp.serialize())
    bus = SessionBus()
    driver = ZkDlogDriverService(pp, device=False)
    nodes = {}
    nodes["issuer"] = TokenNode("issuer", issuer_keys, bus, cc,
                                precision=BIT_LENGTH,
                                auditor_name="auditor", driver=driver)
    nodes["auditor"] = AuditorNode("auditor", auditor_keys, bus, cc,
                                   precision=BIT_LENGTH,
                                   auditor_name="auditor", driver=driver)
    for name in ("alice", "bob", "charlie"):
        nodes[name] = TokenNode(name, new_signing_identity(), bus, cc,
                                precision=BIT_LENGTH,
                                auditor_name="auditor", driver=driver)
    return nodes


def test_zk_issue_transfer_redeem_with_balances(net):
    alice, bob, charlie = net["alice"], net["bob"], net["charlie"]
    tx = alice.issue("issuer", "alice", "USD", hex(1000))
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 1000
    assert bob.balance("USD") == 0

    tx2 = alice.transfer("USD", hex(300), "bob")
    ev = alice.execute(tx2)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 700
    assert bob.balance("USD") == 300

    # bob redeems 100 (change 200 back to bob)
    tx3 = bob.transfer("USD", hex(100), "", redeem=True)
    ev = bob.execute(tx3)
    assert ev.status == "VALID", ev.message
    assert bob.balance("USD") == 200

    # audit trail covers all three transactions; locks released
    auditor = net["auditor"]
    recs = auditor.auditdb.query_transactions()
    assert {r.tx_id for r in recs} == {tx.tx_id, tx2.tx_id, tx3.tx_id}
    assert auditor.auditdb.locked_eids() == []

    # privacy: a non-participant learns no balances from the ledger
    assert charlie.balance("USD") == 0
    assert charlie.tokendb.unspent_tokens() == []

    # the ledger itself stores only commitments: no plaintext value leaks
    for key, raw in net["alice"].cc.ledger.state.items():
        assert b"1000" not in raw and b"0x2bc" not in raw


def test_zk_transfer_gathers_multiple_inputs(net):
    alice, bob = net["alice"], net["bob"]
    for amount in (10, 20, 30):
        assert alice.execute(
            alice.issue("issuer", "alice", "USD", hex(amount))
        ).status == "VALID"
    tx = alice.transfer("USD", hex(55), "bob")
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 5
    assert bob.balance("USD") == 55


def test_auditor_rejects_tampered_opening(net):
    """Metadata opening that doesn't match the commitment fails the audit
    before any signature is produced (crypto/audit/auditor.go:225-246)."""
    alice = net["alice"]
    tx = alice.issue("issuer", "alice", "USD", hex(500))
    md = tx.metadata.issues[0].outputs[0]
    from fabric_token_sdk_tpu.core.zkatdlog.metadata import TokenMetadata

    opening = TokenMetadata.deserialize(md.output_metadata)
    opening.value += 1
    md.output_metadata = opening.serialize()
    from fabric_token_sdk_tpu.services.ttx import TtxError

    with pytest.raises((AuditError, TtxError)):
        alice.execute(tx)


def test_auditor_requires_metadata(net):
    alice = net["alice"]
    tx = alice.issue("issuer", "alice", "USD", hex(5))
    tx.metadata = None
    with pytest.raises(AuditError):
        alice.execute(tx)


def test_receiver_after_redeem_output_still_ingests(net):
    """A redeem output occupies an output index but leaves no ledger key;
    a receiver's ledger-scan ingestion must not stop at the gap."""
    from fabric_token_sdk_tpu.core.fabtoken.driver import OutputSpec
    from fabric_token_sdk_tpu.services.ttx import Transaction
    from fabric_token_sdk_tpu.token.request_builder import Request

    alice, bob = net["alice"], net["bob"]
    assert alice.execute(
        alice.issue("issuer", "alice", "USD", hex(40))).status == "VALID"

    tx_id = Transaction.new_anchor()
    selection = alice.selector.select("alice", "USD", hex(40), tx_id)
    bob_owner, bob_ai = bob.recipient_identity()
    req = Request(tx_id, alice.driver)
    req.transfer(
        selection.tokens,
        [OutputSpec(owner=b"", token_type="USD", value=15),   # redeem @0
         OutputSpec(owner=bob_owner, token_type="USD", value=25,
                    audit_info=bob_ai)],                      # bob @1
        wallet=alice.tokendb.get_ledger_token,
        sender_audit_info=alice.owner_wallet.audit_info_for,
        receivers=[None, "bob"])
    tx = Transaction(tx_id=tx_id, request=req.token_request(),
                     input_owners=["alice"] * len(selection.tokens),
                     input_owner_ids=req.input_owner_ids(),
                     metadata=req.request_metadata(),
                     distribution=req.distribution())
    bob_before = bob.balance("USD")
    # alice does NOT add herself as watcher for bob: bob takes the
    # ledger-scan path (he never assembled or signed this tx)
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert bob.balance("USD") == bob_before + 25
