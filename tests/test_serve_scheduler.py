"""Scheduler/admission policy of the serve/ frontend — pure logic.

Every test runs against a fake ZK backend (or the bare BucketScheduler),
so this module exercises scheduling decisions — deadline expiry,
load shedding, lane priority, dispatch triggers — with no device work.
The bit-identity tests against a REAL verifier live in
tests/test_serve_smoke.py.
"""

import asyncio
import time

import numpy as np

from fabric_token_sdk_tpu.serve import (LANE_BULK, LANE_INTERACTIVE,
                                        STATUS_DEADLINE_MISS, STATUS_OK,
                                        STATUS_SHED_DEADLINE,
                                        STATUS_SHED_QUEUE_FULL,
                                        BucketScheduler, ServeConfig,
                                        VerificationService, VerifyRequest)
from fabric_token_sdk_tpu.serve.request import (KIND_ISSUE, KIND_RANGE,
                                                KIND_TRANSFER)


class _FakeRange:
    def verify(self, proofs, commitments):
        return np.ones(len(proofs), dtype=bool)


class _FakeZK:
    """Accept-everything backend: policy tests need scheduling, not ZK."""

    def __init__(self):
        self._range = _FakeRange()

    def verify_block(self, transfers, issues):
        return (np.ones(len(transfers), dtype=bool),
                np.ones(len(issues), dtype=bool))

    def prewarm_shapes(self, batch_sizes=(1,), include_block=True):
        return {b: 0.0 for b in batch_sizes}


def test_deadline_expiry_rejects_with_status_not_hang():
    # min_batch=2 and one lone request: the wait trigger can never fire,
    # so the request must complete via deadline expiry — promptly, with a
    # terminal status, not by hanging until some batch fills.
    cfg = ServeConfig(buckets=(4,), min_batch=2, max_wait_s=30.0)
    svc = VerificationService(_FakeZK(), config=cfg)

    async def run():
        await svc.start(prewarm=False)
        res = await asyncio.wait_for(
            svc.submit_range(object(), object(), deadline_s=0.05),
            timeout=5.0)
        await svc.stop()
        return res

    res = asyncio.run(run())
    assert res.status == STATUS_DEADLINE_MISS
    assert res.accepted is None


def test_load_shed_when_queue_full():
    cfg = ServeConfig(buckets=(4,), min_batch=3, max_wait_s=30.0,
                      queue_capacity=2)
    svc = VerificationService(_FakeZK(), config=cfg)

    async def run():
        await svc.start(prewarm=False)
        held = [asyncio.create_task(
            svc.submit_range(object(), object(), deadline_s=0.3))
            for _ in range(2)]
        await asyncio.sleep(0.05)  # let both enqueue (below min_batch)
        res3 = await svc.submit_range(object(), object(), deadline_s=0.3)
        first_two = await asyncio.gather(*held)
        await svc.stop()
        return res3, first_two

    res3, first_two = asyncio.run(run())
    assert res3.status == STATUS_SHED_QUEUE_FULL
    # the queued pair still completes with a terminal status
    assert all(r.status in (STATUS_OK, STATUS_DEADLINE_MISS)
               for r in first_two)


def test_admission_sheds_impossible_deadline():
    cfg = ServeConfig(buckets=(4,), service_estimate_s=0.5)
    svc = VerificationService(_FakeZK(), config=cfg)

    async def run():
        await svc.start(prewarm=False)
        res = await svc.submit_range(object(), object(), deadline_s=0.1)
        await svc.stop()
        return res

    res = asyncio.run(run())
    assert res.status == STATUS_SHED_DEADLINE


def test_full_bucket_dispatches_without_waiting():
    cfg = ServeConfig(buckets=(2, 4), max_wait_s=30.0)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for i in range(4):
        sched.push(VerifyRequest(kind=KIND_RANGE, payload=(i,),
                                 lane=LANE_BULK, deadline=now + 60,
                                 enqueue_t=now))
    batch = sched.assemble(now)  # nobody waited, but the bucket is full
    assert len(batch) == 4
    assert sched.depth() == 0


def test_below_min_batch_not_dispatched_until_deadline_pressure():
    cfg = ServeConfig(buckets=(4,), min_batch=2, max_wait_s=0.001,
                      service_estimate_s=0.05)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    sched.push(VerifyRequest(kind=KIND_RANGE, payload=(0,), lane=LANE_BULK,
                             deadline=now + 1.0, enqueue_t=now))
    # max-wait elapsed but rows < min_batch: held
    assert sched.assemble(now + 0.01) == []
    # deadline pressure (deadline - service_estimate passed): dispatched
    # even below min_batch rather than held into a guaranteed miss
    batch = sched.assemble(now + 0.96)
    assert len(batch) == 1


def test_interactive_lane_drains_first():
    cfg = ServeConfig(buckets=(8,))
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    reqs = []
    for i, lane in enumerate([LANE_BULK, LANE_BULK, LANE_INTERACTIVE]):
        r = VerifyRequest(kind=KIND_RANGE, payload=(i,), lane=lane,
                          deadline=now + 10, enqueue_t=now)
        reqs.append(r)
        sched.push(r)
    batch = sched.assemble(now + 1.0)  # max-wait trigger
    assert [r.lane for r in batch] == [LANE_INTERACTIVE, LANE_BULK,
                                       LANE_BULK]
    assert batch[0] is reqs[2]


def test_groups_never_mix_and_actions_demux():
    # transfers + issues batch together (one verify_block); range rows
    # never ride an action batch
    cfg = ServeConfig(buckets=(8,), max_wait_s=0.001)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for kind in (KIND_RANGE, KIND_TRANSFER, KIND_ISSUE, KIND_RANGE):
        sched.push(VerifyRequest(kind=kind, payload=(kind,), lane=LANE_BULK,
                                 deadline=now + 10, enqueue_t=now))
    first = sched.assemble(now + 0.01)
    second = sched.assemble(now + 0.01)
    groups = {tuple(sorted({r.group for r in b})) for b in (first, second)}
    assert groups == {("action",), (KIND_RANGE,)}
    assert len(first) + len(second) == 4

    # the action batch demuxes per-kind through verify_block
    class _CountingZK(_FakeZK):
        def verify_block(self, transfers, issues):
            t = np.array([True] * len(transfers), dtype=bool)
            i = np.array([False] * len(issues), dtype=bool)  # reject issues
            return t, i

    svc = VerificationService(_CountingZK(), config=cfg)

    async def run():
        await svc.start(prewarm=False)
        res_t, res_i = await asyncio.gather(
            svc.submit_transfer(b"raw", [], []),
            svc.submit_issue(b"raw", []))
        await svc.stop()
        return res_t, res_i

    res_t, res_i = asyncio.run(run())
    assert res_t.status == STATUS_OK and res_t.accepted is True
    assert res_i.status == STATUS_OK and res_i.accepted is False


def test_expired_requests_never_occupy_batch_rows():
    cfg = ServeConfig(buckets=(4,), max_wait_s=30.0, min_batch=4)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    dead = VerifyRequest(kind=KIND_RANGE, payload=("dead",), lane=LANE_BULK,
                         deadline=now - 0.01, enqueue_t=now - 1.0)
    live = VerifyRequest(kind=KIND_RANGE, payload=("live",), lane=LANE_BULK,
                         deadline=now + 10, enqueue_t=now)
    sched.push(dead)
    sched.push(live)
    expired = sched.expire(now)
    assert expired == [dead]
    assert sched.depth() == 1


# ---------------------------------------------- tenant deficit round-robin
def _tenant_req(tenant, payload, now, i=0, lane=LANE_BULK):
    return VerifyRequest(kind=KIND_RANGE, payload=(payload,), lane=lane,
                         deadline=now + 60, enqueue_t=now + i * 1e-6,
                         tenant=tenant)


def _range_queue(sched):
    return sched._queues[(KIND_RANGE, LANE_BULK)]


def test_drr_alternates_quantum_sized_runs_between_tenants():
    """A hot tenant no longer owns the drain: with two backlogged
    tenants and quantum=2, service alternates in runs of two — per-
    tenant order stays FIFO."""
    cfg = ServeConfig(buckets=(16,), tenant_quantum=2)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for i in range(6):
        sched.push(_tenant_req("a", f"a{i}", now, i))
    for i in range(6):
        sched.push(_tenant_req("b", f"b{i}", now, 6 + i))
    q = _range_queue(sched)
    drained = [q.popleft() for _ in range(12)]
    assert [r.tenant for r in drained] == ["a", "a", "b", "b"] * 3
    for tenant in ("a", "b"):
        rows = [r.payload[0] for r in drained if r.tenant == tenant]
        assert rows == [f"{tenant}{i}" for i in range(6)]
    assert len(q) == 0


def test_drr_weights_scale_the_per_rotation_grant():
    cfg = ServeConfig(buckets=(16,), tenant_quantum=1,
                      tenant_weights=(("vip", 2.0),))
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for i in range(4):
        sched.push(_tenant_req("vip", f"v{i}", now, i))
        sched.push(_tenant_req("std", f"s{i}", now, 4 + i))
    q = _range_queue(sched)
    drained = [q.popleft().tenant for _ in range(8)]
    # 2:1 service while both are backlogged; std drains its tail after
    # vip empties and retires
    assert drained == ["vip", "vip", "std", "vip", "vip",
                       "std", "std", "std"]


def test_drr_single_tenant_is_exact_fifo_and_head_is_oldest():
    cfg = ServeConfig(buckets=(8,), tenant_quantum=2)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for i in range(5):
        sched.push(_tenant_req("solo", i, now, i))
    q = _range_queue(sched)
    assert q[0].payload == (0,)
    assert [r.payload[0] for r in q] == [0, 1, 2, 3, 4]
    assert [q.popleft().payload[0] for _ in range(5)] == [0, 1, 2, 3, 4]

    # q[0] and iteration present GLOBAL arrival order even when DRR
    # would drain another tenant first (deadline horizons and the
    # expiry sweep must see the true oldest row)
    sched.push(_tenant_req("late", "l0", now + 1.0))
    sched.push(_tenant_req("early", "e0", now - 1.0))
    q = _range_queue(sched)
    assert q[0].payload == ("e0",)
    assert [r.payload[0] for r in q] == ["e0", "l0"]


def test_drr_retire_drops_deficit_gauge_series():
    """Regression: ``rpc_tenant_deficit{tms_id}`` used to live in the
    registry forever once a tenant departed. Retiring (sub-queue
    drained) must remove the gauge series; the drains counter stays
    (cumulative ledger) until the max_tenants LRU evicts it."""
    from fabric_token_sdk_tpu.obs import GLOBAL

    cfg = ServeConfig(buckets=(16,), tenant_quantum=2)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for i in range(4):
        sched.push(_tenant_req("drr-gone", f"g{i}", now, i))
        sched.push(_tenant_req("drr-stays", f"s{i}", now, 4 + i))
    q = _range_queue(sched)
    drained = []
    while len(q) > 2:                      # leave drr-stays backlogged
        drained.append(q.popleft())
    assert {r.tenant for r in drained} >= {"drr-gone"}

    def _series(name, tenant):
        return [(n, lbl) for (n, lbl) in GLOBAL.snapshot()
                if n == name and ("tms_id", tenant) in lbl]

    # drr-gone fully drained -> retired -> its deficit gauge is gone
    assert not _series("rpc_tenant_deficit", "drr-gone"), \
        "retired tenant's deficit gauge leaked"
    # a still-backlogged tenant keeps its gauge and both keep drains
    assert _series("rpc_tenant_deficit", "drr-stays")
    assert _series("serve_tenant_drains_total", "drr-gone")
    assert _series("serve_tenant_drains_total", "drr-stays")


def test_drr_drain_lru_evicts_departed_tenant_counters():
    """Past ``ServeConfig.max_tenants`` distinct drained tenants, the
    least-recently-drained tms_id's counter/gauge series are evicted
    from the registry — per-tenant cardinality is bounded."""
    from fabric_token_sdk_tpu.obs import GLOBAL

    cfg = ServeConfig(buckets=(16,), max_tenants=2)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    for i, tenant in enumerate(("lru-a", "lru-b", "lru-c")):
        sched.push(_tenant_req(tenant, f"p{i}", now, i))
    q = _range_queue(sched)
    for _ in range(3):
        q.popleft()

    def _series(name, tenant):
        return [(n, lbl) for (n, lbl) in GLOBAL.snapshot()
                if n == name and ("tms_id", tenant) in lbl]

    assert not _series("serve_tenant_drains_total", "lru-a"), \
        "LRU-evicted tenant's drains counter leaked"
    assert _series("serve_tenant_drains_total", "lru-b")
    assert _series("serve_tenant_drains_total", "lru-c")


def test_drr_expiry_sweep_keeps_tenant_structure():
    cfg = ServeConfig(buckets=(8,), max_wait_s=30.0, min_batch=8,
                      tenant_quantum=2)
    sched = BucketScheduler(cfg)
    now = time.perf_counter()
    dead = VerifyRequest(kind=KIND_RANGE, payload=("dead",),
                         lane=LANE_BULK, deadline=now - 0.01,
                         enqueue_t=now - 1.0, tenant="a")
    sched.push(dead)
    for i in range(2):
        sched.push(_tenant_req("a", f"a{i}", now, i))
        sched.push(_tenant_req("b", f"b{i}", now, 2 + i))
    assert sched.expire(now) == [dead]
    q = _range_queue(sched)
    drained = [q.popleft() for _ in range(4)]
    assert [r.tenant for r in drained] == ["a", "a", "b", "b"]
    assert sched.depth() == 0
