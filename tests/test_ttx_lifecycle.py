"""Multi-party ttx lifecycle over the in-process session bus + ledger.

Mirrors the reference's fungible integration flow (integration/token/
fungible/tests.go:277 TestAll shape): issue -> transfer -> redeem with
balance and audit assertions, plus failure paths (insufficient funds,
non-auditor refusing audits).
"""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.selector import InsufficientFunds
from fabric_token_sdk_tpu.services.ttx import SessionBus


@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    validator = fabtoken.new_validator(pp, Deserializer())
    ledger = MemoryLedger()
    cc = TokenChaincode(validator, ledger, pp.serialize())
    bus = SessionBus()
    nodes = {}
    nodes["issuer"] = TokenNode("issuer", issuer_keys, bus, cc,
                                auditor_name="auditor")
    nodes["auditor"] = AuditorNode("auditor", auditor_keys, bus, cc,
                                   auditor_name="auditor")
    for name in ("alice", "bob", "charlie"):
        nodes[name] = TokenNode(name, new_signing_identity(), bus, cc,
                                auditor_name="auditor")
    return nodes


def test_issue_transfer_redeem_with_balances(net):
    alice, bob = net["alice"], net["bob"]
    # issue 1000 USD to alice
    tx = alice.issue("issuer", "alice", "USD", hex(1000))
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 1000
    assert bob.balance("USD") == 0

    # alice -> bob 300 (change 700 back to alice)
    tx2 = alice.transfer("USD", hex(300), "bob")
    ev = alice.execute(tx2)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 700
    assert bob.balance("USD") == 300

    # bob redeems 100
    tx3 = bob.transfer("USD", hex(100), "", redeem=True)
    ev = bob.execute(tx3)
    assert ev.status == "VALID", ev.message
    assert bob.balance("USD") == 200

    # audit trail covers all three transactions
    auditor = net["auditor"]
    recs = auditor.auditdb.query_transactions()
    assert {r.tx_id for r in recs} == {tx.tx_id, tx2.tx_id, tx3.tx_id}
    assert auditor.auditdb.locked_eids() == []  # released at finality


def test_insufficient_funds(net):
    alice = net["alice"]
    tx = alice.issue("issuer", "alice", "USD", hex(50))
    assert alice.execute(tx).status == "VALID"
    with pytest.raises(InsufficientFunds):
        alice.transfer("USD", hex(100), "bob")
    # funds untouched and locks released
    assert alice.balance("USD") == 50
    tx2 = alice.transfer("USD", hex(25), "bob")
    assert alice.execute(tx2).status == "VALID"


def test_transfer_multiple_inputs_gathers_coins(net):
    alice, bob = net["alice"], net["bob"]
    for amount in (10, 20, 30):
        assert alice.execute(
            alice.issue("issuer", "alice", "USD", hex(amount))
        ).status == "VALID"
    tx = alice.transfer("USD", hex(55), "bob")
    ev = alice.execute(tx)
    assert ev.status == "VALID", ev.message
    assert alice.balance("USD") == 5
    assert bob.balance("USD") == 55


def test_status_tracking(net):
    alice = net["alice"]
    tx = alice.issue("issuer", "alice", "USD", hex(10))
    assert alice.execute(tx).status == "VALID"
    from fabric_token_sdk_tpu.services.db.sqldb import TxStatus
    assert alice.ttxdb.get_status(tx.tx_id) == TxStatus.CONFIRMED


def test_non_auditor_node_refuses_audit(net):
    from fabric_token_sdk_tpu.services.ttx import TtxError
    alice = net["alice"]
    tx = alice.issue("issuer", "alice", "USD", hex(10))
    alice.auditor_name = "bob"  # bob is not an auditor
    with pytest.raises(TtxError):
        alice.execute(tx)
