"""tokengen CLI golden round-trips + NFT layer tests (reference
cmd/tokengen, token/services/nfttx)."""

import json

import pytest

from fabric_token_sdk_tpu.cmd.tokengen import build_parser, main
from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, TokenChaincode
from fabric_token_sdk_tpu.services.nfttx import (NFTService, NoResults,
                                                 marshal_state, state_id,
                                                 unmarshal_state)
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus


# ----------------------------------------------------------------- tokengen

def _write_identity(tmp_path, name):
    from cryptography.hazmat.primitives import serialization

    keys = new_signing_identity()
    pem = keys.private_key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    p = tmp_path / f"{name}.pem"
    p.write_bytes(pem)
    return p, bytes(keys.identity)


def test_tokengen_fabtoken_roundtrip(tmp_path, capsys):
    issuer_pem, issuer_der = _write_identity(tmp_path, "issuer")
    rc = main(["gen", "fabtoken", "--precision", "32",
               "--issuer", str(issuer_pem), "--output", str(tmp_path)])
    assert rc == 0
    out = tmp_path / "fabtoken_pp.json"
    raw = out.read_bytes()
    pp = fabtoken.PublicParams.deserialize(raw)
    assert pp.quantity_precision == 32
    assert pp.max_token == (1 << 32) - 1
    assert [bytes(i) for i in pp.issuer_ids] == [issuer_der]
    # golden stability: re-serialize is byte-identical
    assert pp.serialize() == raw
    # registry accepts the generated file directly
    from fabric_token_sdk_tpu.core.registry import default_registry

    assert default_registry().new_bundle(raw).label == "fabtoken"


def test_tokengen_dlog_roundtrip(tmp_path, capsys):
    from fabric_token_sdk_tpu.crypto.setup import PublicParams

    issuer_pem, _ = _write_identity(tmp_path, "issuer")
    auditor_pem, _ = _write_identity(tmp_path, "aud")
    rc = main(["gen", "dlog", "--bits", "16", "--issuer", str(issuer_pem),
               "--auditor", str(auditor_pem), "--tpu-batch-size", "256",
               "--output", str(tmp_path)])
    assert rc == 0
    raw = (tmp_path / "zkatdlog_pp.json").read_bytes()
    pp = PublicParams.deserialize(raw)
    pp.validate()
    assert pp.range_proof_params.bit_length == 16
    assert pp.tpu_batch.batch_size == 256
    assert pp.serialize() == raw

    # pp print reports the right summary
    rc = main(["pp", "print", str(tmp_path / "zkatdlog_pp.json")])
    assert rc == 0
    text = capsys.readouterr().out
    assert "identifier: zkatdlog" in text and "bit_length: 16" in text


def test_tokengen_base_exponent_selects_bits(tmp_path):
    rc = main(["gen", "dlog", "--base", "2", "--exponent", "5",
               "--output", str(tmp_path)])  # 2^5 = 32
    from fabric_token_sdk_tpu.crypto.setup import PublicParams

    assert rc == 0
    pp = PublicParams.deserialize((tmp_path / "zkatdlog_pp.json").read_bytes())
    assert pp.range_proof_params.bit_length == 32


def test_tokengen_rejects_unsupported_bits(tmp_path):
    assert main(["gen", "dlog", "--bits", "17",
                 "--output", str(tmp_path)]) == 2


def test_tokengen_certifier_keygen(tmp_path):
    """cmd/tokengen certifier-keygen (cobra/certfier/keypairgen.go)."""
    assert main(["gen", "dlog", "--bits", "16",
                 "--output", str(tmp_path)]) == 0
    rc = main(["certifier-keygen", "--driver", "dlog",
               "--pppath", str(tmp_path / "zkatdlog_pp.json"),
               "--output", str(tmp_path / "cert")])
    assert rc == 0
    from fabric_token_sdk_tpu.services.identity.x509 import (
        keypair_from_pem)

    kp = keypair_from_pem((tmp_path / "cert" / "certifier_sk.pem")
                          .read_bytes())
    sig = kp.sign(b"certify")
    kp.verifier().verify(b"certify", sig)
    # driver/pp mismatch is rejected
    assert main(["certifier-keygen", "--driver", "fabtoken",
                 "--pppath", str(tmp_path / "zkatdlog_pp.json"),
                 "--output", str(tmp_path)]) == 2


def test_tokengen_artifacts_gen(tmp_path):
    """cmd/tokengen artifacts gen (cobra/artifactgen): topology ->
    identities + wired pp + manifest."""
    import json

    topo = {"driver": "fabtoken", "precision": 32,
            "nodes": [{"name": "issuer", "role": "issuer"},
                      {"name": "aud", "role": "auditor"},
                      {"name": "alice"}, {"name": "bob"}]}
    tf = tmp_path / "topology.json"
    tf.write_text(json.dumps(topo))
    out = tmp_path / "artifacts"
    assert main(["artifacts", "gen", "--topology", str(tf),
                 "--output", str(out)]) == 0

    manifest = json.loads((out / "manifest.json").read_text())
    assert [n["name"] for n in manifest["nodes"]] == \
        ["issuer", "aud", "alice", "bob"]
    # pp is wired with the generated issuer/auditor identities
    from fabric_token_sdk_tpu.services.identity.x509 import keypair_from_pem

    pp = fabtoken.PublicParams.deserialize((out / "pp.json").read_bytes())
    issuer_kp = keypair_from_pem(
        (out / "crypto" / "issuer" / "sk.pem").read_bytes())
    aud_kp = keypair_from_pem((out / "crypto" / "aud" / "sk.pem")
                              .read_bytes())
    assert [bytes(i) for i in pp.issuer_ids] == [bytes(issuer_kp.identity)]
    assert bytes(pp.auditor) == bytes(aud_kp.identity)
    # empty topology is rejected
    tf.write_text(json.dumps({"nodes": []}))
    assert main(["artifacts", "gen", "--topology", str(tf),
                 "--output", str(out)]) == 2


def test_tokengen_update_preserves_material(tmp_path):
    from fabric_token_sdk_tpu.crypto.setup import PublicParams

    assert main(["gen", "dlog", "--bits", "16",
                 "--output", str(tmp_path)]) == 0
    path = tmp_path / "zkatdlog_pp.json"
    before = PublicParams.deserialize(path.read_bytes())
    assert main(["update", str(path)]) == 0
    after = PublicParams.deserialize(path.read_bytes())
    # generators unchanged by an update (identities/generators preserved)
    from fabric_token_sdk_tpu.crypto import serialization as ser

    assert ser.g1_to_bytes(after.pedersen_generators[0]) == \
        ser.g1_to_bytes(before.pedersen_generators[0])


# -------------------------------------------------------------------- nfttx

@pytest.fixture
def net():
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    bus = SessionBus()
    nodes = {
        "issuer": TokenNode("issuer", issuer_keys, bus, cc,
                            auditor_name="auditor"),
        "auditor": AuditorNode("auditor", auditor_keys, bus, cc,
                               auditor_name="auditor"),
        "alice": TokenNode("alice", new_signing_identity(), bus, cc,
                           auditor_name="auditor"),
        "bob": TokenNode("bob", new_signing_identity(), bus, cc,
                         auditor_name="auditor"),
    }
    return nodes


def test_nft_state_marshalling_roundtrip():
    state = {"model": "house", "address": "5th avenue"}
    token_type = marshal_state(state)
    restored = unmarshal_state(token_type)
    assert restored["model"] == "house"
    assert state_id(restored)  # unique ID stamped
    # two marshals of the same state get DIFFERENT ids (uniqueness)
    assert state_id(unmarshal_state(marshal_state(state))) != \
        state_id(restored)


def test_nft_issue_transfer_query(net):
    alice_svc = NFTService(net["alice"])
    bob_svc = NFTService(net["bob"])
    state = alice_svc.issue("issuer", "alice",
                            {"model": "house", "address": "5th avenue"})
    sid = state_id(state)

    # query by arbitrary key (qe.go:52 QueryByKey)
    assert alice_svc.query_by_key("address", "5th avenue")["model"] == \
        "house"

    alice_svc.transfer(sid, "bob")
    assert bob_svc.query_by_key("model", "house")
    with pytest.raises(NoResults):
        alice_svc.query_by_key("model", "house")  # alice no longer owns it


def test_nft_unknown_query(net):
    with pytest.raises(NoResults):
        NFTService(net["alice"]).query_by_key("model", "missing")


def test_tokengen_utils_pp_print(tmp_path, capsys):
    """The nested `utils pp print -i FILE` verb (cmd/tokengen/main.go:49 ->
    cobra/pp/utils.go -> printpp/print.go) mirrors `pp print`."""
    issuer_pem, _ = _write_identity(tmp_path, "issuer")
    rc = main(["gen", "fabtoken", "--precision", "16",
               "--issuer", str(issuer_pem), "--output", str(tmp_path)])
    assert rc == 0
    out = tmp_path / "fabtoken_pp.json"
    rc = main(["utils", "pp", "print", "-i", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "fabtoken" in text and "16" in text
