"""SLO burn-rate monitor and device profiler (obs/slo.py, obs/profiling.py).

Everything runs against a private MetricsProvider and a fake clock —
no device, no wall-clock sleeps, no global-registry leakage.
"""

import jax.numpy as jnp

from fabric_token_sdk_tpu.obs import (DeviceProfiler, MetricsProvider,
                                      SloMonitor, SloPolicy,
                                      TenantSloMonitor, TenantSloPolicy,
                                      jain_index)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _monitor(policy=None, **kw):
    clock = _Clock()
    provider = MetricsProvider()
    mon = SloMonitor(policy=policy or SloPolicy(), provider=provider,
                     clock=clock, **kw)
    return mon, clock, provider


def _gauge(provider, name, **labels):
    vals = [v for (n, lbl), v in provider.snapshot().items()
            if n == name and all((k, str(val)) in
                                 [(a, str(b)) for a, b in lbl]
                                 for k, val in labels.items())]
    assert vals, f"gauge {name}{labels} not published"
    return vals[0]


# ------------------------------------------------------------ SloMonitor
def test_window_stats_availability_and_p99():
    mon, clock, provider = _monitor(
        policy=SloPolicy(windows=(60.0, 300.0), min_volume=8))
    for i in range(99):
        mon.record(True, latency_s=(i + 1) / 1000.0)
        clock.advance(0.1)
    mon.record(False)
    assert _gauge(provider, "slo_availability_ratio", window="60s") == 0.99
    assert _gauge(provider, "slo_window_requests", window="60s") == 100
    p99 = _gauge(provider, "slo_p99_seconds", window="60s")
    assert abs(p99 - 0.099) <= 0.002
    # burn = (1 - 0.99) / (1 - 0.999) = 10x budget
    burn = _gauge(provider, "slo_error_budget_burn_rate", window="60s")
    assert abs(burn - 10.0) < 1e-6


def test_events_roll_out_of_the_window():
    mon, clock, provider = _monitor(policy=SloPolicy(windows=(60.0, 300.0)))
    mon.record(False)
    clock.advance(100.0)  # failure now outside the 60s window
    mon.record(True, latency_s=0.01)
    assert _gauge(provider, "slo_availability_ratio", window="60s") == 1.0
    # ...but still inside the 300s window
    assert _gauge(provider, "slo_availability_ratio", window="300s") == 0.5
    clock.advance(400.0)  # beyond the horizon: pruned entirely
    mon.record(True, latency_s=0.01)
    assert _gauge(provider, "slo_window_requests", window="300s") == 1


def test_fast_burn_trips_edge_triggered_and_recovers():
    trips, recoveries = [], []
    mon, clock, provider = _monitor(
        policy=SloPolicy(min_volume=10, fast_burn=14.4),
        on_fast_burn=lambda: trips.append(clock.t),
        on_recover=lambda: recoveries.append(clock.t))
    # 100% failures over both windows: burn = 1/0.001 = 1000 >> 14.4
    for _ in range(20):
        mon.record(False)
        clock.advance(0.01)
    assert mon.fast_burn_active and mon.trips == 1
    assert len(trips) == 1, "hook must fire once per episode, not per record"
    assert _gauge(provider, "slo_fast_burn_active") == 1

    # recovery: every failure ages out of both windows
    clock.advance(400.0)
    mon.record(True, latency_s=0.01)
    assert not mon.fast_burn_active
    assert recoveries and _gauge(provider, "slo_fast_burn_active") == 0
    counters = {n: v for (n, _), v in provider.snapshot().items()
                if n == "slo_fast_burn_trips_total"}
    assert list(counters.values()) == [1.0]


def test_min_volume_gates_the_trip():
    mon, clock, _ = _monitor(policy=SloPolicy(min_volume=32))
    for _ in range(31):
        mon.record(False)
        clock.advance(0.01)
    assert not mon.fast_burn_active, "a 31-request blip must not page"
    mon.record(False)
    assert mon.fast_burn_active


def test_bind_breaker_forces_open_on_fast_burn():
    class _Breaker:
        state = "closed"

        def force_open(self):
            self.state = "open"

        def force_close(self):
            self.state = "closed"

    mon, clock, _ = _monitor(policy=SloPolicy(min_volume=4))
    breaker = _Breaker()
    mon.bind_breaker(breaker)
    for _ in range(8):
        mon.record(False)
        clock.advance(0.01)
    assert breaker.state == "open"
    clock.advance(400.0)
    mon.record(True, latency_s=0.01)
    assert breaker.state == "closed"


def test_summary_shape():
    mon, clock, _ = _monitor()
    for ok in (True, True, False):
        mon.record(ok, latency_s=0.02 if ok else None)
        clock.advance(0.5)
    doc = mon.summary()
    assert doc["availability_target"] == 0.999
    assert set(doc["windows"]) == {"60s", "300s"}
    w = doc["windows"]["60s"]
    assert w["requests"] == 3 and 0 < w["availability"] < 1
    assert w["p99_s"] == 0.02


# ------------------------------------------------------ TenantSloMonitor
def _tenant_monitor(policy=None, **kw):
    clock = _Clock()
    provider = MetricsProvider()
    mon = TenantSloMonitor(policy=policy or TenantSloPolicy(),
                           provider=provider, clock=clock, **kw)
    return mon, clock, provider


def test_tenant_windows_are_independent():
    mon, clock, provider = _tenant_monitor(
        policy=TenantSloPolicy(windows=(60.0, 300.0), min_volume=8))
    for i in range(100):
        mon.record("good", True, latency_s=0.01)
        mon.record("bad", i % 2 == 0, latency_s=0.01)
        clock.advance(0.1)
    assert _gauge(provider, "slo_tenant_availability", tms_id="good") == 1.0
    assert _gauge(provider, "slo_tenant_availability", tms_id="bad") == 0.5
    # bad's burn: (1 - 0.5) / 0.001 = 500x budget; good burns nothing
    assert abs(_gauge(provider, "slo_tenant_burn_rate", tms_id="bad",
                      window="60s") - 500.0) < 1e-6
    assert _gauge(provider, "slo_tenant_burn_rate", tms_id="good",
                  window="60s") == 0.0
    assert _gauge(provider, "slo_tenant_budget_remaining",
                  tms_id="good") == 1.0
    assert _gauge(provider, "slo_tenant_budget_remaining",
                  tms_id="bad") == 0.0
    assert mon.shedding("bad") and not mon.shedding("good")


def test_tenant_fast_burn_trips_edge_triggered_and_recovers():
    trips, recoveries = [], []
    mon, clock, provider = _tenant_monitor(
        policy=TenantSloPolicy(min_volume=10, fast_burn=14.4),
        on_fast_burn=trips.append, on_recover=recoveries.append)
    for _ in range(20):
        mon.record("hot", False)
        mon.record("victim", True, latency_s=0.01)
        clock.advance(0.01)
    assert trips == ["hot"], "hook fires once per episode, with the tms_id"
    assert mon.shedding("hot") and not mon.shedding("victim")
    summ = mon.summary()
    assert summ["tenants"]["hot"]["fast_burn_active"]
    assert summ["tenants"]["hot"]["trips"] == 1

    # recovery: hot's failures age out of both windows
    clock.advance(400.0)
    mon.record("hot", True, latency_s=0.01)
    assert recoveries == ["hot"]
    assert not mon.shedding("hot")


def test_tenant_min_volume_gates_the_trip():
    mon, clock, _ = _tenant_monitor(
        policy=TenantSloPolicy(min_volume=32))
    for _ in range(31):
        mon.record("blip", False)
        clock.advance(0.01)
    assert not mon.shedding("blip"), "a 31-request blip must not shed"
    mon.record("blip", False)
    assert mon.shedding("blip")


def test_tenant_lru_eviction_bounds_cardinality_and_series():
    evicted = []
    mon, clock, provider = _tenant_monitor(
        policy=TenantSloPolicy(max_tenants=3), on_evict=evicted.append)
    for t in ("a", "b", "c"):
        mon.record(t, True, latency_s=0.01)
        clock.advance(0.01)
    mon.record("a", True, latency_s=0.01)   # refresh a: b is now LRU
    mon.record("d", True, latency_s=0.01)   # evicts b
    assert evicted == ["b"]
    assert mon.tenants() == ["c", "a", "d"]
    assert mon.evictions == 1
    # every slo_tenant_* series for the evicted tms_id is gone
    leaked = [(n, lbl) for (n, lbl) in provider.snapshot()
              if n.startswith("slo_tenant_") and ("tms_id", "b") in lbl]
    assert not leaked, f"evicted tenant left series behind: {leaked}"
    assert _gauge(provider, "slo_tenant_availability", tms_id="a") == 1.0
    counters = [v for (n, _), v in provider.snapshot().items()
                if n == "slo_tenant_evictions_total"]
    assert counters == [1.0]


def test_note_shed_counts_without_feeding_the_window():
    mon, clock, _ = _tenant_monitor(
        policy=TenantSloPolicy(min_volume=4))
    for _ in range(8):
        mon.record("t", True, latency_s=0.01)
        clock.advance(0.01)
    mon.note_shed("t", rows=100)
    summ = mon.summary()["tenants"]["t"]
    assert summ["sheds"] == 100
    assert summ["requests"] == 8, "sheds must not count as window events"
    assert not mon.shedding("t"), "sheds must not burn the tenant's budget"


def test_fairness_indices_published():
    mon, clock, provider = _tenant_monitor()
    # equal service: J = 1.0 on both bases
    for t in ("a", "b", "c", "d"):
        for _ in range(10):
            mon.record(t, True, latency_s=0.01)
    assert _gauge(provider, "slo_fairness_index", basis="throughput") == 1.0
    assert _gauge(provider, "slo_fairness_index", basis="p99") == 1.0
    # starve d into 100x the latency: the p99 basis must drop
    for _ in range(10):
        mon.record("d", True, latency_s=1.0)
    assert _gauge(provider, "slo_fairness_index", basis="p99") < 0.9
    doc = mon.summary()
    assert 0.0 < doc["fairness"]["p99"] < 1.0
    assert doc["fairness"]["throughput"] < 1.0  # d now served 2x the rest


def test_jain_index_extremes():
    assert jain_index([]) == 1.0
    assert jain_index([5.0]) == 1.0
    assert jain_index([3.0, 3.0, 3.0]) == 1.0
    # one tenant takes everything: J -> 1/n
    assert abs(jain_index([100.0, 0.0, 0.0, 0.0]) - 0.25) < 1e-9


def test_eval_interval_batches_evaluation():
    mon, clock, provider = _tenant_monitor(
        policy=TenantSloPolicy(min_volume=1, eval_interval_s=10.0))
    mon.record("t", False)          # first record: eval runs immediately
    for _ in range(5):
        mon.record("t", True, latency_s=0.01)  # within the interval
    assert _gauge(provider, "slo_tenant_availability", tms_id="t") == 0.0
    clock.advance(11.0)
    mon.record("t", True, latency_s=0.01)      # interval elapsed: re-eval
    assert _gauge(provider, "slo_tenant_availability", tms_id="t") == 6 / 7


# -------------------------------------------------------- DeviceProfiler
def test_record_compile_and_cache_events():
    provider = MetricsProvider()
    prof = DeviceProfiler(provider=provider)
    prof.record_compile("serve_prewarm", 256, 12.5)
    prof.record_cache_event("serve_dispatch", hit=False)
    prof.record_cache_event("serve_dispatch", hit=True)
    prof.record_cache_event("serve_dispatch", hit=True)
    snap = provider.snapshot()
    hist = [v for (n, lbl), v in snap.items()
            if n == "profile_compile_seconds"][0]
    assert hist["count"] == 1 and hist["sum"] == 12.5
    events = {dict(lbl)["event"]: v for (n, lbl), v in snap.items()
              if n == "profile_compile_cache_total"}
    assert events == {"miss": 1.0, "hit": 2.0}
    assert prof.summary()["compile_seconds"] == {"serve_prewarm:256": 12.5}


def test_capture_kernel_cost_lowers_without_compiling():
    provider = MetricsProvider()
    prof = DeviceProfiler(provider=provider)

    def fn(x):
        return (x * 2.0 + 1.0).sum()

    cost = prof.capture_kernel_cost("demo", 16, fn,
                                    jnp.ones((16,), jnp.float32))
    assert cost is not None and cost.get("flops", 0) > 0
    assert _val(provider, "profile_bucket_flops") > 0
    summ = prof.summary()["bucket_costs"]["demo:16"]
    assert summ["flops"] == float(cost["flops"])


def _val(provider, name):
    return [v for (n, _), v in provider.snapshot().items() if n == name][0]


def test_capture_bucket_cost_duck_types_and_never_raises():
    provider = MetricsProvider()
    prof = DeviceProfiler(provider=provider)

    class _NoCost:
        pass

    class _Raises:
        def kernel_cost(self, bucket):
            raise RuntimeError("backend exploded")

    class _ListShaped:
        def kernel_cost(self, bucket):
            return [{"flops": 7.0, "bytes accessed": 3.0}]

    assert prof.capture_bucket_cost(_NoCost(), 16) is None
    assert prof.capture_bucket_cost(_Raises(), 16) is None
    cost = prof.capture_bucket_cost(_ListShaped(), 16)
    assert cost == {"flops": 7.0, "bytes accessed": 3.0}
    assert _val(provider, "profile_bucket_flops") == 7.0
    assert _val(provider, "profile_bucket_bytes") == 3.0


def test_capture_fused_costs_duck_types_and_never_raises():
    """The fused-program capture mirrors capture_bucket_cost's contract:
    duck-typed hook, None on shims without it or on backend failure,
    passthrough of the {kind: cost} map (pass12_fused et al. publish on
    the existing profile_* families inside kernel_cost_fused itself)."""
    prof = DeviceProfiler(provider=MetricsProvider())

    class _NoHook:
        pass

    class _Raises:
        def kernel_cost_fused(self, bucket):
            raise RuntimeError("backend exploded")

    class _Fused:
        def kernel_cost_fused(self, bucket):
            return {"pass12_fused": {"flops": 5.0}}

    assert prof.capture_fused_costs(_NoHook(), 16) is None
    assert prof.capture_fused_costs(_Raises(), 16) is None
    assert prof.capture_fused_costs(_Fused(), 16) == {
        "pass12_fused": {"flops": 5.0}}


def test_memory_watermark_never_raises_on_cpu():
    provider = MetricsProvider()
    prof = DeviceProfiler(provider=provider)
    out = prof.record_memory_watermark()  # CPU: memory_stats() is None
    assert isinstance(out, dict)
    doc = prof.summary()
    assert "memory" in doc and isinstance(doc["memory"], dict)
