"""Driver registry, TMSProvider resolution order, TMS facade, Request
builder (reference token/core/service.go:29, token/core/tms.go:63-274,
token/tms.go:32, token/request.go:225-341,968,1145)."""

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.core.fabtoken.driver import OutputSpec
from fabric_token_sdk_tpu.core.registry import (RegistryError, TMSID,
                                                TMSProvider, default_registry)
from fabric_token_sdk_tpu.crypto import setup as zk_setup


@pytest.fixture(scope="module")
def fab_pp_raw():
    return fabtoken.setup(64).serialize()


@pytest.fixture(scope="module")
def zk_pp_raw():
    return zk_setup.setup(16).serialize()


def test_registry_dispatches_on_identifier(fab_pp_raw, zk_pp_raw):
    reg = default_registry()
    assert reg.labels() == ["fabtoken", "zkatdlog"]
    b1 = reg.new_bundle(fab_pp_raw)
    assert b1.label == "fabtoken"
    assert b1.validator is not None and b1.services is not None
    b2 = reg.new_bundle(zk_pp_raw)
    assert b2.label == "zkatdlog"
    assert b2.public_params.range_proof_params.bit_length == 16


def test_registry_unknown_identifier(fab_pp_raw):
    reg = default_registry()
    with pytest.raises(RegistryError, match="no driver found"):
        reg.new_bundle(b'{"identifier": "martian", "raw": ""}')
    with pytest.raises(RegistryError, match="already registered"):
        reg.register("fabtoken", lambda raw: None)


def test_provider_resolution_order(fab_pp_raw, zk_pp_raw):
    """opts -> storage -> fetcher (core/tms.go:207-274)."""
    fetched = []

    def fetcher(tmsid):
        fetched.append(tmsid)
        return fab_pp_raw if tmsid.network == "net-fetch" else None

    prov = TMSProvider(default_registry(), fetcher=fetcher)

    # 1. explicit opts win
    tms = prov.get_management_service(TMSID("net-a"), pp_raw=zk_pp_raw)
    assert tms.label == "zkatdlog"
    # cached per TMSID
    assert prov.get_management_service(TMSID("net-a")) is tms

    # 2. storage
    prov.store_public_params(TMSID("net-b"), fab_pp_raw)
    assert prov.get_management_service(TMSID("net-b")).label == "fabtoken"

    # 3. fetcher
    assert prov.get_management_service(TMSID("net-fetch")).label == "fabtoken"
    assert fetched == [TMSID("net-fetch")]

    # unresolvable
    with pytest.raises(RegistryError, match="cannot resolve"):
        prov.get_management_service(TMSID("net-missing"))


def test_provider_update_drops_cache(fab_pp_raw, zk_pp_raw):
    prov = TMSProvider(default_registry())
    tmsid = TMSID("net", "ch", "ns")
    tms1 = prov.get_management_service(tmsid, pp_raw=fab_pp_raw)
    assert tms1.label == "fabtoken"
    prov.update(tmsid, zk_pp_raw)
    tms2 = prov.get_management_service(tmsid)
    assert tms2 is not tms1 and tms2.label == "zkatdlog"


def test_tms_facade_surface(zk_pp_raw):
    prov = TMSProvider(default_registry())
    tms = prov.get_management_service(TMSID("net"), pp_raw=zk_pp_raw)
    ppm = tms.public_parameters_manager()
    ppm.validate()
    assert ppm.precision() == 16
    assert ppm.serialize() == tms.public_parameters_manager().serialize()
    assert tms.validator() is not None
    assert tms.deserializer() is not None


def test_request_builder_fabtoken(fab_pp_raw):
    prov = TMSProvider(default_registry())
    tms = prov.get_management_service(TMSID("net"), pp_raw=fab_pp_raw)
    req = tms.new_request("anchor-1")
    req.issue(b"issuer-id", [OutputSpec(owner=b"alice", token_type="USD",
                                        value=100)], receivers=["alice"])
    tr = req.token_request()
    assert len(tr.issues) == 1 and not tr.transfers
    # plaintext driver: no metadata, no distribution
    assert req.request_metadata() is None
    assert req.distribution() == []
    # message-to-sign covers the anchor
    m1 = req.marshal_to_sign()
    assert m1.endswith(b"anchor-1")
    # audit check is a no-op for plaintext actions
    req.audit_check()


def test_request_builder_zkatdlog_with_audit(zk_pp_raw):
    prov = TMSProvider(default_registry())
    tms = prov.get_management_service(TMSID("net"), pp_raw=zk_pp_raw)
    req = tms.new_request("anchor-2")
    req.issue(b"issuer-id",
              [OutputSpec(owner=b"alice", token_type="USD", value=10,
                          audit_info=b"alice"),
               OutputSpec(owner=b"bob", token_type="USD", value=20,
                          audit_info=b"bob")],
              receivers=["alice", "bob"])
    md = req.request_metadata()
    assert md is not None and len(md.issues) == 1
    assert [(r, i) for r, i, _ in req.distribution()] == [("alice", 0),
                                                          ("bob", 1)]
    # the auditor-side check passes on honest metadata (request.go:1145)
    req.audit_check(input_tokens=[])

    # and rejects a tampered opening
    from fabric_token_sdk_tpu.core.zkatdlog.metadata import TokenMetadata

    opening = TokenMetadata.deserialize(
        md.issues[0].outputs[0].output_metadata)
    opening.value += 1
    md.issues[0].outputs[0].output_metadata = opening.serialize()
    with pytest.raises(Exception, match="opening"):
        req.audit_check(input_tokens=[])


class TestDriverSPIConformance:
    """Both shipped drivers satisfy the stated SPI contracts
    (driver/api.py vs reference token/driver/tms.go:31-46): a third
    driver can be written against the protocols alone."""

    def test_bundles_satisfy_service_contracts(self, fab_pp_raw, zk_pp_raw):
        from fabric_token_sdk_tpu.driver import api

        reg = default_registry()
        for raw in (fab_pp_raw, zk_pp_raw):
            b = reg.new_bundle(raw)
            svc = b.services
            assert isinstance(svc, api.IssueService)
            assert isinstance(svc, api.TransferService)
            assert isinstance(svc, api.TokensService)
            assert isinstance(svc, api.AuditorService)
            assert isinstance(svc, api.DriverService)
            assert isinstance(b.validator, api.Validator)
            assert isinstance(b.deserializer, api.Deserializer)
            assert isinstance(b.public_params, api.PublicParameters)

    def test_tms_satisfies_entrypoint_contract(self, zk_pp_raw):
        from fabric_token_sdk_tpu.driver import api
        from fabric_token_sdk_tpu.services.identity.registry import \
            WalletService as ConcreteWalletService

        prov = TMSProvider(default_registry())
        tms = prov.get_management_service(TMSID("net"), pp_raw=zk_pp_raw)
        assert isinstance(tms, api.TokenManagerService)
        assert isinstance(tms.public_parameters_manager(),
                          api.PublicParamsManager)
        assert isinstance(ConcreteWalletService({}), api.WalletService)

    def test_third_driver_registrable_against_spi_alone(self, fab_pp_raw):
        """A minimal driver written only against driver/api.py protocols
        registers and resolves through the registry."""
        import json

        from fabric_token_sdk_tpu.core.registry import DriverBundle
        from fabric_token_sdk_tpu.driver import api

        class MiniPP:
            def serialize(self) -> bytes:
                return b'{"identifier": "mini"}'

            def validate(self) -> None:
                pass

        class MiniService:
            label = "mini"

            def assemble_issue(self, issuer_identity, outputs):
                return None, None

            def assemble_transfer(self, input_rows, outputs, wallet=None,
                                  sender_audit_info=None):
                return None, None

            def extract_outputs(self, action, openings=None):
                return []

            def parse_ledger_output(self, raw, opening=None):
                return None

            def audit_check(self, request, metadata, input_tokens, tx_id):
                pass

        class MiniValidator:
            def unmarshal_actions(self, raw):
                return []

            def verify_token_request_from_raw(self, get_state, anchor, raw):
                return [], {}

        svc = MiniService()
        assert isinstance(svc, api.DriverService)
        assert isinstance(MiniValidator(), api.Validator)

        reg = default_registry()
        reg.register("mini", lambda raw: DriverBundle(
            label="mini", public_params=MiniPP(), services=svc,
            validator=MiniValidator(), deserializer=None))
        b = reg.new_bundle(b'{"identifier": "mini"}')
        assert b.label == "mini"
        assert json.loads(b.public_params.serialize())["identifier"] == "mini"
