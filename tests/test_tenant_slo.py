"""SLO-aware per-tenant shedding through the serve frontend
(serve/admission.TenantShedPolicy + obs/slo.TenantSloMonitor wiring).

A StubZK-backed VerificationService with a fake-clocked TenantSloMonitor:
when the hot tenant's fast-burn trips, NEW work from that tenant sheds
with the distinct ``shed_tenant_slo`` status while other tenants are
served untouched; when the hot tenant's windows recover it un-sheds.
No device, no wall-clock sleeps.
"""

import asyncio

from fabric_token_sdk_tpu.obs import (GLOBAL, MetricsProvider,
                                      TenantSloMonitor, TenantSloPolicy)
from fabric_token_sdk_tpu.serve import (STATUS_OK, STATUS_SHED_TENANT_SLO,
                                        ServeConfig, StubZK,
                                        TenantShedPolicy,
                                        VerificationService)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _monitor(clock, **policy_kw):
    policy_kw.setdefault("min_volume", 8)
    return TenantSloMonitor(policy=TenantSloPolicy(**policy_kw),
                            provider=MetricsProvider(), clock=clock)


def _burn(monitor, tenant, clock, n=16):
    """Trip the tenant's fast-burn: 100% failures over both windows."""
    for _ in range(n):
        monitor.record(tenant, False)
        clock.advance(0.01)


def _svc(monitor, **cfg_kw):
    cfg = ServeConfig(buckets=(4, 8), max_wait_s=0.001, **cfg_kw)
    return VerificationService(StubZK(), config=cfg, tenant_slo=monitor)


def test_hot_tenant_sheds_victims_admitted():
    clock = _Clock()
    monitor = _monitor(clock)
    svc = _svc(monitor)
    _burn(monitor, "hot", clock)
    assert monitor.shedding("hot")

    async def run():
        await svc.start(prewarm=False)
        hot = await svc.submit_range(True, None, tenant="hot")
        victim = await svc.submit_range(True, None, tenant="victim")
        await svc.stop()
        return hot, victim

    hot, victim = asyncio.run(run())
    assert hot.status == STATUS_SHED_TENANT_SLO and hot.accepted is None
    assert victim.status == STATUS_OK and victim.accepted is True
    summ = svc.tenant_status()
    assert summ["enabled"] and summ["shed_policy_enabled"]
    assert summ["tenants"]["hot"]["sheds"] == 1
    # shed rows are counted in the stable per-tenant family
    sheds = [v for (n, lbl), v in GLOBAL.snapshot().items()
             if n == "serve_tenant_sheds_total" and ("tms_id", "hot") in lbl]
    assert sheds and sheds[0] >= 1


def test_whole_frame_sheds_for_the_hot_tenant_only():
    clock = _Clock()
    monitor = _monitor(clock)
    svc = _svc(monitor)
    _burn(monitor, "hot", clock)

    async def run():
        await svc.start(prewarm=False)
        hot = await svc.submit_batch("range", [(True, None)] * 4,
                                     tenant="hot")
        victim = await svc.submit_batch("range", [(True, None)] * 4,
                                        tenant="victim")
        await svc.stop()
        return hot, victim

    hot, victim = asyncio.run(run())
    assert all(r.status == STATUS_SHED_TENANT_SLO for r in hot)
    assert all(r.status == STATUS_OK and r.accepted for r in victim)
    assert svc.tenant_status()["tenants"]["hot"]["sheds"] == 4


def test_shed_does_not_self_sustain_and_recovery_unsheds():
    clock = _Clock()
    monitor = _monitor(clock)
    svc = _svc(monitor)
    _burn(monitor, "hot", clock)

    async def run():
        await svc.start(prewarm=False)
        shed = await svc.submit_range(True, None, tenant="hot")
        # sheds must not feed the window: burn stays where the real
        # failures put it, and aging those out recovers the tenant
        requests_before = monitor.summary()["tenants"]["hot"]["requests"]
        clock.advance(400.0)
        monitor.record("hot", True, 0.01)
        assert not monitor.shedding("hot")
        served = await svc.submit_range(True, None, tenant="hot")
        await svc.stop()
        return shed, requests_before, served

    shed, requests_before, served = asyncio.run(run())
    assert shed.status == STATUS_SHED_TENANT_SLO
    assert requests_before == 16, "a shed must not count as a window event"
    assert served.status == STATUS_OK and served.accepted is True


def test_no_tenant_shed_env_disables_the_policy(monkeypatch):
    monkeypatch.setenv("FTS_NO_TENANT_SHED", "1")
    clock = _Clock()
    monitor = _monitor(clock)
    svc = _svc(monitor)                   # policy reads env at construction
    _burn(monitor, "hot", clock)
    assert monitor.shedding("hot"), "the monitor still observes and trips"
    assert not svc.admission.tenant_shed.enabled

    async def run():
        await svc.start(prewarm=False)
        res = await svc.submit_range(True, None, tenant="hot")
        await svc.stop()
        return res

    res = asyncio.run(run())
    assert res.status == STATUS_OK, "disabled policy must not shed"
    assert svc.tenant_status()["shed_policy_enabled"] is False


def test_shed_policy_without_monitor_never_sheds():
    policy = TenantShedPolicy(None, enabled=True)
    assert not policy.should_shed("anyone")


def test_eviction_drops_serve_tenant_series():
    clock = _Clock()
    monitor = _monitor(clock, max_tenants=2)
    svc = _svc(monitor)

    async def run():
        await svc.start(prewarm=False)
        for t in ("evict-a", "evict-b", "evict-c"):
            res = await svc.submit_range(True, None, tenant=t)
            assert res.ok
        await svc.stop()

    asyncio.run(run())
    assert monitor.evictions >= 1
    assert "evict-a" not in monitor.tenants()
    # the service's on_evict hook dropped the serve-layer series too
    leaked = [(n, lbl) for (n, lbl) in GLOBAL.snapshot()
              if n.startswith("serve_tenant_")
              and ("tms_id", "evict-a") in lbl]
    assert not leaked, f"evicted tenant left serve series behind: {leaked}"
    live = [(n, lbl) for (n, lbl) in GLOBAL.snapshot()
            if n == "serve_tenant_e2e_seconds"
            and ("tms_id", "evict-c") in lbl]
    assert live, "resident tenants keep their series"
