"""Standalone common-driver plumbing (core/common/plumbing.py):
token loaders + ownership multiplexer (reference
token/core/common/loaders.go:47-231, authrorization.go:18-141)."""

import pytest

from fabric_token_sdk_tpu.core.common.plumbing import (
    AuthorizationMultiplexer, EscrowOwnership, TokenLoadError,
    VaultTokenLoader, WalletOwnership)
from fabric_token_sdk_tpu.services.db.sqldb import TokenDB
from fabric_token_sdk_tpu.services.identity.multisig import unwrap, \
    wrap_identities
from fabric_token_sdk_tpu.services.identity.typed import \
    unmarshal_typed_identity
from fabric_token_sdk_tpu.services.identity.wallet import X509OwnerWallet
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.token.model import ID


@pytest.fixture
def wallet():
    return X509OwnerWallet(new_signing_identity())


def test_vault_loader_loads_and_fails_like_reference():
    db = TokenDB(":memory:")
    tid = ID("tx1", 0)
    db.store_token(tid, b"owner", "USD", "0x10", ["alice"],
                   ledger_format="fabtoken", ledger_token=b"tok",
                   ledger_metadata=b"md")
    loader = VaultTokenLoader(db)
    assert loader(tid) == (b"tok", b"md")
    assert loader.load_tokens([tid]) == [(b"tok", b"md")]
    with pytest.raises(TokenLoadError, match="does not exist"):
        loader(ID("tx-unknown", 9))
    db.delete_token(tid, spent_by="tx2")
    with pytest.raises(TokenLoadError, match="spent or never committed"):
        loader.load_tokens([tid])


def test_ownership_mux_wallet_then_escrow(wallet):
    other = X509OwnerWallet(new_signing_identity())
    mine, _ = wallet.recipient_identity()
    theirs, _ = other.recipient_identity()
    mux = AuthorizationMultiplexer(
        WalletOwnership("alice", wallet),
        EscrowOwnership("alice", wallet, unwrap))

    assert mux.is_mine(mine) == (["alice"], True)
    assert mux.is_mine(theirs) == ([], False)
    # co-owned escrow identity lands in the .ms wallet
    escrow = wrap_identities(mine, theirs)
    assert mux.is_mine(escrow) == (["alice.ms"], True)
    # escrow I am not part of is not mine
    foreign = wrap_identities(theirs, theirs)
    assert mux.is_mine(foreign) == ([], False)


def test_mux_auditor_flag_and_owner_type(wallet):
    mine, _ = wallet.recipient_identity()
    aud = AuthorizationMultiplexer(
        WalletOwnership("a", wallet, auditor=True),
        unmarshal_typed=unmarshal_typed_identity)
    not_aud = AuthorizationMultiplexer(WalletOwnership("a", wallet))
    assert aud.am_i_an_auditor() and not not_aud.am_i_an_auditor()

    theirs, _ = X509OwnerWallet(new_signing_identity()).recipient_identity()
    t, _ = aud.owner_type(wrap_identities(mine, theirs))
    assert t == "ms"
    assert aud.owner_type(mine)[0] in ("plain", "x509")


def test_mux_satisfies_spi_contract(wallet):
    from fabric_token_sdk_tpu.driver import api

    mux = AuthorizationMultiplexer(WalletOwnership("a", wallet))
    assert isinstance(mux, api.Authorization)
