"""Heartbeat writer + stall detector (obs/heartbeat.py), driven entirely
by fake clocks and temp files: stamp format, torn-line tolerant reads,
per-phase deadlines, edge-triggered latching, and the incident wiring.
"""

import json
import os

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.obs.heartbeat import (FileHeartbeatReader,
                                                Heartbeat, StallDetector,
                                                incident_on_stall, read_last)
from fabric_token_sdk_tpu.obs.journal import Journal

# ---------------------------------------------------------------- writer


def test_beat_appends_flushed_stamps(tmp_path):
    j = Journal()
    path = tmp_path / "hb.jsonl"
    hb = Heartbeat(path, journal=j, clock=lambda: 100.5)
    hb.beat("jax_init", "8 devices")
    hb.beat("verify")
    # flushed per line: readable without close()
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    stamp = json.loads(lines[0])
    assert stamp == {"t": 100.5, "phase": "jax_init",
                     "detail": "8 devices", "pid": os.getpid()}
    assert hb.last()["phase"] == "verify"
    # every beat is mirrored into the flight recorder
    assert [e["phase"] for e in j.tail()] == ["jax_init", "verify"]
    hb.close()


def test_pathless_heartbeat_stays_in_memory():
    hb = Heartbeat(journal=None)
    hb.beat("x")
    assert hb.last()["phase"] == "x"


def test_read_last_tolerates_torn_final_line(tmp_path):
    path = tmp_path / "hb.jsonl"
    hb = Heartbeat(path, journal=None, clock=lambda: 7.0)
    hb.beat("setup")
    hb.beat("verify")
    hb.close()
    # the writer died mid-write: a torn, unparseable final line
    with open(path, "a") as f:
        f.write('{"t": 9.0, "phase": "tam')
    stamp = read_last(path)
    assert stamp["phase"] == "verify" and stamp["t"] == 7.0
    assert read_last(tmp_path / "missing.jsonl") is None
    reader = FileHeartbeatReader(path)
    assert reader()["phase"] == "verify"


# -------------------------------------------------------- stall detection


def _detector(reader, clock, **kw):
    kw.setdefault("provider", GLOBAL)
    kw.setdefault("grace_s", 5.0)
    kw.setdefault("default_deadline_s", 10.0)
    return StallDetector(reader, clock=clock, **kw)


def test_stall_fires_once_per_stamp_then_relatches_on_progress():
    now = [0.0]
    hb = Heartbeat(journal=None, clock=lambda: now[0])
    det = _detector(hb.last, lambda: now[0],
                    deadlines={"verify": 2.0})
    hb.beat("verify")
    now[0] = 1.0
    assert det.check() is None           # under the phase deadline
    now[0] = 3.5
    phase, age = det.check()             # over it: fires
    assert phase == "verify" and age == 3.5
    assert det.check() is None           # latched: no re-fire
    assert det.stalls == 1
    hb.beat("verify")                    # progress clears the latch
    now[0] = 7.0
    phase, age = det.check()
    assert phase == "verify" and det.stalls == 2


def test_default_deadline_applies_to_unlisted_phase():
    now = [0.0]
    hb = Heartbeat(journal=None, clock=lambda: now[0])
    det = _detector(hb.last, lambda: now[0], deadlines={"verify": 2.0},
                    default_deadline_s=50.0)
    hb.beat("compile")
    now[0] = 20.0
    assert det.check() is None           # 20s < default 50s
    now[0] = 60.0
    assert det.check() == ("compile", 60.0)


def test_no_heartbeat_trips_after_grace():
    now = [0.0]
    det = _detector(lambda: None, lambda: now[0], grace_s=5.0)
    assert det.check() is None           # within grace: not started yet
    now[0] = 6.0
    phase, age = det.check()
    assert phase == StallDetector.NO_HEARTBEAT and age == 6.0
    assert det.check() is None           # latched


def test_on_stall_callback_and_incident_wiring(tmp_path):
    now = [0.0]
    hb = Heartbeat(journal=None, clock=lambda: now[0])
    j = Journal(min_interval_s=0.0)
    j.configure(tmp_path)
    fired = []
    det = _detector(hb.last, lambda: now[0], default_deadline_s=1.0,
                    on_stall=lambda phase, age: (
                        fired.append(phase),
                        incident_on_stall(j)(phase, age)))
    hb.beat("sharded_msm")
    now[0] = 2.0
    assert det.check() is not None
    assert fired == ["sharded_msm"]
    snaps = list(tmp_path.glob("incident_heartbeat_stall_*.json"))
    assert len(snaps) == 1
    doc = json.loads(snaps[0].read_text())
    assert "sharded_msm" in doc["reason"]
