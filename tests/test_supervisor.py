"""Process supervisor (resilience/supervisor.py): escalation ladder,
RTO accounting, stall detection, seeded backoff/kill schedules — all
pure-logic with fake handles + a fake clock (tier-1), plus real
process-kill drills against the sidecar worker (slow)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fabric_token_sdk_tpu.obs import GLOBAL
from fabric_token_sdk_tpu.obs.journal import Journal
from fabric_token_sdk_tpu.resilience import (RUNG_COLD_RESTART,
                                             RUNG_GIVE_UP, RUNG_RESTART,
                                             ChildSpec, KillSchedule,
                                             Supervisor, SupervisorPolicy)
from fabric_token_sdk_tpu.resilience.supervisor import COLD_CACHE_ENV

pytestmark = pytest.mark.crash

#: Above the kernel's default pid_max (4194304 would be the first
#: impossible pid; anything >= it can never name a live process), so the
#: supervisor's SIGUSR1 poke on a stalled fake handle hits nothing.
_FAKE_PID = 4_194_313


class _FakeHandle:
    """multiprocessing.Process duck-type driven by the test."""

    def __init__(self, pid=_FAKE_PID):
        self.pid = pid
        self.exitcode = None
        self.terminated = 0
        self.killed = 0

    def is_alive(self):
        return self.exitcode is None

    def die(self, code=-9):
        self.exitcode = code

    def terminate(self):
        self.terminated += 1
        self.die(-15)

    def kill(self):
        self.killed += 1
        self.die(-9)

    def join(self, timeout=None):
        pass


def _fake_supervisor(policy, **kw):
    """Supervisor on a settable clock; poll() is driven manually (the
    monitor thread never starts)."""
    clk = {"t": 0.0}
    sup = Supervisor(policy=policy, clock=lambda: clk["t"],
                     journal=Journal(min_interval_s=0.0), **kw)
    return sup, clk


def _tick(sup, clk, t):
    clk["t"] = t
    sup.poll()


def _stamp(path, t, phase, pid):
    with open(path, "a") as f:
        f.write(json.dumps({"t": t, "phase": phase, "pid": pid}) + "\n")


# -------------------------------------------------------------- ladder
def test_escalation_ladder_restart_cold_giveup(monkeypatch):
    GLOBAL.reset()
    monkeypatch.setenv(COLD_CACHE_ENV[0], "/tmp/warm-cache")
    policy = SupervisorPolicy(seed=3, backoff_base_s=0.01,
                              backoff_cap_s=0.02, cold_after=1,
                              give_up_after=2, stable_reset_s=1e9)
    sup, clk = _fake_supervisor(policy)
    spawned, handles, gave_up = [], [], []

    def start(ctx):
        # capture what a spawn callable observes: the RestartContext and
        # whether the warm-cache env was cleared for this spawn
        spawned.append((ctx, os.environ.get(COLD_CACHE_ENV[0])))
        h = _FakeHandle()
        handles.append(h)
        return h

    h0 = _FakeHandle()
    handles.append(h0)
    sup.add_child(ChildSpec(
        "w", start=start,
        on_give_up=lambda name, n: gave_up.append((name, n))), handle=h0)

    # failure 1 -> warm restart, env untouched
    handles[-1].die(code=1)
    _tick(sup, clk, 100.0)
    assert sup.status()["w"]["state"] == "backoff"
    _tick(sup, clk, 110.0)
    ctx, env = spawned[-1]
    assert (ctx.rung, ctx.cold, env) == (RUNG_RESTART, False,
                                         "/tmp/warm-cache")

    # failure 2 (> cold_after=1) -> cold restart with caches cleared
    # during the spawn and restored right after
    handles[-1].die(code=1)
    _tick(sup, clk, 120.0)
    _tick(sup, clk, 130.0)
    ctx, env = spawned[-1]
    assert (ctx.rung, ctx.cold, env) == (RUNG_COLD_RESTART, True, None)
    assert os.environ[COLD_CACHE_ENV[0]] == "/tmp/warm-cache"

    # failure 3 (> give_up_after=2) -> give up: incident, callback, no
    # further spawns ever
    handles[-1].die(code=1)
    _tick(sup, clk, 140.0)
    st = sup.status()["w"]
    assert (st["state"], st["rung"]) == ("failed", RUNG_GIVE_UP)
    assert gave_up == [("w", 3)]
    assert any("supervisor_give_up" in str(e) for e in sup.journal.tail())
    n = len(spawned)
    _tick(sup, clk, 10_000.0)
    assert len(spawned) == n

    snap = GLOBAL.snapshot()
    key = ("crash_failures_total", (("cause", "exit"), ("child", "w")))
    assert snap[key] == 3
    assert snap[("crash_restarts_total",
                 (("child", "w"), ("rung", RUNG_RESTART)))] == 1
    assert snap[("crash_restarts_total",
                 (("child", "w"), ("rung", RUNG_COLD_RESTART)))] == 1
    assert snap[("crash_escalations_total",
                 (("child", "w"), ("rung", RUNG_COLD_RESTART)))] == 1
    assert snap[("crash_escalations_total",
                 (("child", "w"), ("rung", RUNG_GIVE_UP)))] == 1
    assert snap[("crash_child_up", (("child", "w"),))] == 0


def test_stable_uptime_clears_ladder():
    GLOBAL.reset()
    policy = SupervisorPolicy(backoff_base_s=0.01, backoff_cap_s=0.02,
                              cold_after=1, give_up_after=10,
                              stable_reset_s=5.0)
    sup, clk = _fake_supervisor(policy)
    handles = []

    def start(ctx):
        handles.append(_FakeHandle())
        return handles[-1]

    h0 = _FakeHandle()
    handles.append(h0)
    sup.add_child(ChildSpec("w", start=start), handle=h0)

    handles[-1].die(code=1)
    _tick(sup, clk, 0.0)
    _tick(sup, clk, 1.0)                       # respawned, failures=1
    assert sup.status()["w"]["failures"] == 1
    _tick(sup, clk, 7.0)                       # 6s stable >= 5s: cleared
    assert sup.status()["w"]["failures"] == 0

    # the next failure starts the ladder from scratch: warm, not cold
    handles[-1].die(code=1)
    _tick(sup, clk, 8.0)
    _tick(sup, clk, 9.0)
    st = sup.status()["w"]
    assert (st["failures"], st["rung"]) == (1, RUNG_RESTART)


# ----------------------------------------------------------------- RTO
def test_rto_measured_without_heartbeat_file():
    GLOBAL.reset()
    policy = SupervisorPolicy(backoff_base_s=0.01, backoff_cap_s=0.02,
                              stable_reset_s=1e9)
    sup, clk = _fake_supervisor(policy)
    h0 = _FakeHandle()
    sup.add_child(ChildSpec("w", start=lambda ctx: _FakeHandle()),
                  handle=h0)
    h0.die(code=1)
    _tick(sup, clk, 10.0)                      # detection instant
    _tick(sup, clk, 12.0)                      # respawn
    _tick(sup, clk, 12.5)                      # liveness == recovery
    hist = GLOBAL.histogram("crash_rto_seconds", child="w")
    assert hist.n == 1
    assert abs(hist.total - 2.5) < 1e-6


def test_rto_waits_for_fresh_heartbeat_from_new_pid(tmp_path):
    GLOBAL.reset()
    hb = str(tmp_path / "w.hb.jsonl")
    policy = SupervisorPolicy(backoff_base_s=0.01, backoff_cap_s=0.02,
                              stable_reset_s=1e9)
    sup, clk = _fake_supervisor(policy)
    h1 = _FakeHandle(pid=_FAKE_PID + 1)

    _stamp(hb, 0.0, "ready", _FAKE_PID)
    h0 = _FakeHandle(pid=_FAKE_PID)
    sup.add_child(ChildSpec("w", start=lambda ctx: h1,
                            heartbeat_file=hb, default_deadline_s=1e9,
                            grace_s=1e9), handle=h0)
    h0.die(code=1)
    _tick(sup, clk, 5.0)                       # detection instant
    _tick(sup, clk, 6.0)                       # respawn as pid+1
    _tick(sup, clk, 7.0)
    hist = GLOBAL.histogram("crash_rto_seconds", child="w")
    # the dead pid's stale stamp must not count as recovery
    assert hist.n == 0
    _stamp(hb, 8.0, "ready", h1.pid)           # first beat of the NEW pid
    _tick(sup, clk, 9.0)
    assert hist.n == 1
    assert abs(hist.total - 4.0) < 1e-6        # 9.0 - detection at 5.0


# --------------------------------------------------------------- stall
def test_stall_kills_and_restarts_the_wedged_child(tmp_path):
    GLOBAL.reset()
    hb = str(tmp_path / "w.hb.jsonl")
    policy = SupervisorPolicy(backoff_base_s=0.01, backoff_cap_s=0.02,
                              stable_reset_s=1e9)
    sup, clk = _fake_supervisor(policy)
    _stamp(hb, 100.0, "ready", _FAKE_PID)
    h0 = _FakeHandle()
    sup.add_child(ChildSpec("w", start=lambda ctx: _FakeHandle(),
                            heartbeat_file=hb,
                            deadlines={"ready": 2.0},
                            default_deadline_s=1e9, grace_s=1e9),
                  handle=h0)
    clk["t"] = 100.5
    sup.poll()                                 # fresh stamp: healthy
    assert sup.status()["w"]["state"] == "running"

    _tick(sup, clk, 110.0)                     # 10s-old "ready" beat
    st = sup.status()["w"]
    assert st["last_cause"] == "stall"
    assert st["state"] == "backoff"
    # the wedged-but-alive process was taken down before the restart
    assert h0.terminated == 1 and not h0.is_alive()
    key = ("crash_failures_total", (("cause", "stall"), ("child", "w")))
    assert GLOBAL.snapshot()[key] == 1


# ------------------------------------------------------------- seeding
def test_backoff_schedule_is_deterministic_per_seed():
    def restart_at(seed):
        policy = SupervisorPolicy(seed=seed, backoff_base_s=0.05,
                                  backoff_cap_s=2.0)
        sup, clk = _fake_supervisor(policy)
        h = _FakeHandle()
        sup.add_child(ChildSpec("w", start=lambda ctx: _FakeHandle()),
                      handle=h)
        h.die(code=1)
        _tick(sup, clk, 50.0)
        return sup._children["w"].restart_at

    assert restart_at(7) == restart_at(7)
    assert restart_at(7) > 50.0


def test_kill_schedule_is_seeded_and_bounded():
    a = KillSchedule(seed=5, duration_s=100.0, kills=3, stops=2)
    b = KillSchedule(seed=5, duration_s=100.0, kills=3, stops=2)
    assert a.events == b.events                # replayable run-over-run
    assert a.events == sorted(a.events)
    assert len(a.events) == 5
    names = [name for _, name in a.events]
    assert names.count("SIGKILL") == 3 and names.count("SIGSTOP") == 2
    for offset, _ in a.events:
        assert 15.0 <= offset <= 85.0          # middle of the window
    c = KillSchedule(seed=6, duration_s=100.0, kills=3, stops=2)
    assert c.events != a.events


def test_kill_schedule_delivers_and_counts():
    GLOBAL.reset()
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        ks = KillSchedule(seed=1, duration_s=0.4, kills=1, stops=0)
        ks.start(lambda: proc.pid)
        ks.join(timeout_s=10.0)
        proc.wait(timeout=10)
        assert proc.returncode == -signal.SIGKILL
        assert [(s, p) for _, s, p in ks.delivered] \
            == [("SIGKILL", proc.pid)]
        key = ("crash_injected_signals_total", (("signal", "SIGKILL"),))
        assert GLOBAL.snapshot()[key] == 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


# ------------------------------------------- real process-kill drills
def _worker_client(hb):
    from fabric_token_sdk_tpu.serve.worker import stub_zk_factory

    from fabric_token_sdk_tpu.serve import WorkerClient

    return WorkerClient(stub_zk_factory, heartbeat_path=hb,
                        call_timeout_s=60.0)


@pytest.mark.slow
def test_supervisor_restarts_sigkilled_worker(tmp_path):
    GLOBAL.reset()
    hb = str(tmp_path / "w.hb.jsonl")
    worker = _worker_client(hb)

    def respawn(ctx=None):
        # a dead pid's stale stamp would trip the stall watch against
        # the fresh child; with no file, grace_s covers the boot
        try:
            os.remove(hb)
        except FileNotFoundError:
            pass
        return worker.spawn(ctx)

    h = respawn()
    worker.wait_ready(timeout_s=60.0)
    sup = Supervisor(policy=SupervisorPolicy(backoff_base_s=0.05,
                                             backoff_cap_s=0.2),
                     poll_s=0.05)
    sup.add_child(ChildSpec("w", start=respawn, heartbeat_file=hb,
                            default_deadline_s=120.0, grace_s=120.0),
                  handle=h)
    sup.start()
    try:
        pid0 = worker.pid
        assert worker._range.verify([1, 0, 1], list("abc")).tolist() \
            == [True, False, True]
        os.kill(pid0, signal.SIGKILL)
        deadline = time.monotonic() + 60
        verdicts = None
        while time.monotonic() < deadline:
            if worker.pid is not None and worker.pid != pid0:
                try:
                    verdicts = worker._range.verify([1, 0, 1],
                                                    list("abc")).tolist()
                    break
                except Exception:  # noqa: BLE001 — still rebooting
                    pass
            time.sleep(0.05)
        # the replacement serves bit-identical verdicts
        assert verdicts == [True, False, True]
        assert worker.pid != pid0
        snap = GLOBAL.snapshot()
        key = ("crash_failures_total",
               (("cause", "exit"), ("child", "w")))
        assert snap[key] >= 1
    finally:
        sup.stop(terminate_children=True)
        worker.stop()


@pytest.mark.slow
def test_supervisor_recovers_sigstopped_worker(tmp_path):
    """SIGSTOP is the stealth failure: the process stays alive but its
    beats freeze. Recovery must come from the stall watch, which must
    escalate to SIGKILL (a queued SIGTERM never reaches a stopped
    process)."""
    GLOBAL.reset()
    hb = str(tmp_path / "w.hb.jsonl")
    worker = _worker_client(hb)

    def respawn(ctx=None):
        try:
            os.remove(hb)
        except FileNotFoundError:
            pass
        return worker.spawn(ctx)

    h = respawn()
    worker.wait_ready(timeout_s=60.0)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and worker.phase() != "ready":
        time.sleep(0.05)
    assert worker.phase() == "ready"

    sup = Supervisor(policy=SupervisorPolicy(backoff_base_s=0.05,
                                             backoff_cap_s=0.2),
                     poll_s=0.05)
    sup.add_child(ChildSpec("w", start=respawn, heartbeat_file=hb,
                            deadlines={"ready": 1.5},
                            default_deadline_s=60.0, grace_s=120.0),
                  handle=h)
    sup.start()
    try:
        pid0 = worker.pid
        os.kill(pid0, signal.SIGSTOP)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if worker.pid is not None and worker.pid != pid0 \
                    and worker.phase() == "ready":
                break
            time.sleep(0.05)
        assert worker.pid is not None and worker.pid != pid0
        assert worker._range.verify([1, 0], list("ab")).tolist() \
            == [True, False]
        key = ("crash_failures_total",
               (("cause", "stall"), ("child", "w")))
        assert GLOBAL.snapshot()[key] >= 1
    finally:
        sup.stop(terminate_children=True)
        worker.stop()


@pytest.mark.slow
def test_service_degrades_to_host_fallback_when_worker_dies():
    """Degraded mode: with the worker dead and no supervisor running,
    every verdict rides the host fallback (bit-identical) instead of
    erroring — availability degrades, it never zeroes."""
    import asyncio

    from fabric_token_sdk_tpu.resilience import ResilienceConfig
    from fabric_token_sdk_tpu.serve import (STATUS_OK, ServeConfig,
                                            VerificationService)
    from fabric_token_sdk_tpu.serve.worker import StubHostFallback

    worker = _worker_client(None)
    worker.spawn()
    worker.wait_ready(timeout_s=60.0)
    resil = ResilienceConfig(retry_attempts=2, retry_base_s=0.01,
                             retry_cap_s=0.02, breaker_min_volume=2,
                             breaker_reset_s=60.0,
                             watchdog_timeout_s=None)
    svc = VerificationService(
        worker,
        config=ServeConfig(buckets=(4,), max_wait_s=0.005,
                           default_deadline_s=30.0),
        resilience=resil, fallback=StubHostFallback())

    async def run():
        await svc.start(prewarm=False)
        first = await svc.submit_range(1, "c")
        assert first.accepted is True and first.served_by == "device"
        worker._proc.kill()
        worker._proc.join()
        outs = await asyncio.gather(
            *[svc.submit_range(i % 2, f"c{i}") for i in range(6)])
        await svc.stop(timeout_s=10.0)
        return outs

    try:
        outs = asyncio.run(run())
        for i, res in enumerate(outs):
            assert res.status == STATUS_OK
            assert res.served_by == "host"
            assert res.accepted is bool(i % 2)
    finally:
        worker.stop()
