"""Input/Output streams, external-wallet signing, Request.upgrade verb.

Covers reference token/stream.go:1-354 (filter chains the apps and the
auditor use), token/services/ttx/external.go:19-210 (remote-wallet signing
protocol), and token/request.go:389 (the Upgrade verb).
"""

import threading

import pytest

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.core.zkatdlog.driver import ZkDlogDriverService
from fabric_token_sdk_tpu.crypto import setup as zk_setup
from fabric_token_sdk_tpu.driver import TokenRequest
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import (MemoryLedger,
                                                       TokenChaincode)
from fabric_token_sdk_tpu.services import ttx_external as ext
from fabric_token_sdk_tpu.token.model import ID, UnspentToken
from fabric_token_sdk_tpu.token.request_builder import (Request,
                                                        RequestBuilderError)
from fabric_token_sdk_tpu.token.stream import (Input, InputStream, Output,
                                               OutputStream, OwnerStream)


# ---------------------------------------------------------------------------
# streams
# ---------------------------------------------------------------------------

def _outputs():
    return [
        Output(owner=b"alice", type="USD", quantity="0x10", index=0,
               enrollment_id="alice@org1", revocation_handler="rh-a"),
        Output(owner=b"bob", type="USD", quantity="0x20", index=1,
               enrollment_id="bob@org1", revocation_handler="rh-b"),
        Output(owner=b"alice", type="EUR", quantity="0x30", index=2,
               enrollment_id="alice@org1", revocation_handler="rh-a"),
        Output(owner=b"", type="USD", quantity="0x5", index=3),  # redeem
    ]


class TestOutputStream:
    def test_filters_and_sum(self):
        s = OutputStream(_outputs())
        assert s.count() == 4
        assert s.by_recipient(b"alice").count() == 2
        assert s.by_type("USD").count() == 3
        assert s.by_type("USD").by_recipient(b"bob").sum() == 0x20
        assert s.sum() == 0x10 + 0x20 + 0x30 + 0x5
        # original stream untouched by filtering
        assert s.count() == 4

    def test_dedup_projections(self):
        s = OutputStream(_outputs())
        assert s.enrollment_ids() == ["alice@org1", "bob@org1"]
        assert s.token_types() == ["USD", "EUR"]
        assert s.revocation_handles() == ["rh-a", "rh-b"]

    def test_at_and_id(self):
        s = OutputStream(_outputs())
        assert s.at(1).owner == b"bob"
        tid = s.at(1).id("tx-9")
        assert (tid.tx_id, tid.index) == ("tx-9", 1)

    def test_by_enrollment_id(self):
        s = OutputStream(_outputs())
        assert s.by_enrollment_id("alice@org1").sum() == 0x40


class _QS:
    def __init__(self, mine):
        self.mine = mine

    def is_mine(self, token_id):
        return token_id in self.mine


class TestInputStream:
    def _inputs(self):
        return [
            Input(id=ID("t1", 0), owner=b"alice", type="USD",
                  quantity="0x10", enrollment_id="alice@org1"),
            Input(id=ID("t2", 1), owner=b"bob", type="EUR",
                  quantity="0x20", enrollment_id="bob@org1"),
            Input(id=ID("t3", 0), owner=b"alice", type="USD",
                  quantity="0x1", enrollment_id="alice@org1"),
        ]

    def test_filters_ids_sum(self):
        s = InputStream(_QS(set()), self._inputs())
        assert s.count() == 3
        assert [t.tx_id for t in s.ids()] == ["t1", "t2", "t3"]
        assert s.by_type("USD").sum() == 0x11
        assert s.by_enrollment_id("bob@org1").count() == 1
        assert s.enrollment_ids() == ["alice@org1", "bob@org1"]
        assert s.token_types() == ["USD", "EUR"]

    def test_owner_stream_dedups(self):
        s = InputStream(_QS(set()), self._inputs())
        owners = s.owners()
        assert isinstance(owners, OwnerStream)
        assert owners.count() == 2
        assert owners.owners() == [b"alice", b"bob"]

    def test_is_any_mine(self):
        inputs = self._inputs()
        assert InputStream(_QS({ID("t2", 1)}), inputs).is_any_mine()
        assert not InputStream(_QS(set()), inputs).is_any_mine()


# ---------------------------------------------------------------------------
# external wallet signing
# ---------------------------------------------------------------------------

class TestExternalWalletSigner:
    def test_sign_round_trip_and_done(self):
        server_stream, client_stream = ext.QueuePairStream.pair()
        keys = new_signing_identity()

        def provider(party):
            return keys if bytes(party) == bytes(keys.identity) else None

        client = ext.StreamExternalWalletSignerClient(provider, client_stream)
        worker = threading.Thread(target=client.respond, daemon=True)
        worker.start()

        server = ext.StreamExternalWalletSignerServer(server_stream)
        sigma = server.sign(bytes(keys.identity), b"endorse-me")
        server.done()
        worker.join(timeout=10)
        assert not worker.is_alive()
        keys.verifier().verify(b"endorse-me", sigma)

    def test_client_rejects_unknown_party(self):
        server_stream, client_stream = ext.QueuePairStream.pair()
        client = ext.StreamExternalWalletSignerClient(
            lambda party: None, client_stream)
        server = ext.StreamExternalWalletSignerServer(server_stream)
        errs = []

        def run():
            try:
                client.respond()
            except ext.ExternalWalletError as e:
                errs.append(e)

        worker = threading.Thread(target=run, daemon=True)
        worker.start()
        server.stream.send(ext._encode(ext.SIG_REQUEST, {
            "party": b"ghost".hex(), "message": b"m".hex()}))
        worker.join(timeout=10)
        assert errs and "no signer" in str(errs[0])

    def test_server_rejects_wrong_response_type(self):
        server_stream, client_stream = ext.QueuePairStream.pair()
        server = ext.StreamExternalWalletSignerServer(server_stream)
        client_stream.send(ext._encode(ext.DONE, None))
        with pytest.raises(ext.ExternalWalletError, match="expected sign"):
            server.sign(b"p", b"m")


# ---------------------------------------------------------------------------
# Request.upgrade verb
# ---------------------------------------------------------------------------

BIT_LENGTH = 16


@pytest.fixture(scope="module")
def upgrade_world():
    """Old-format plaintext token on the ledger; zkatdlog pp now active."""
    issuer, auditor = new_signing_identity(), new_signing_identity()
    alice, bob = new_signing_identity(), new_signing_identity()

    fab_pp = fabtoken.setup(BIT_LENGTH)
    fab_pp.issuer_ids = [issuer.identity]
    fab_pp.auditor = bytes(auditor.identity)
    ledger = MemoryLedger()
    fab_cc = TokenChaincode(fabtoken.new_validator(fab_pp, Deserializer()),
                            ledger, fab_pp.serialize())
    issue = fabtoken.IssueAction(
        issuer=issuer.identity,
        outputs=[fabtoken.Output(bytes(alice.identity), "USD", "0x4d")])
    req = TokenRequest(issues=[issue.serialize()])
    msg = req.message_to_sign(b"old1")
    req.auditor_signatures = [auditor.sign(msg)]
    req.signatures = [issuer.sign(msg)]
    assert fab_cc.process_request("old1", req.to_bytes()).status == "VALID"

    zk_pp = zk_setup.setup(BIT_LENGTH)
    zk_pp.issuer_ids = [issuer.identity]
    zk_pp.auditor = bytes(auditor.identity)
    from fabric_token_sdk_tpu.core import zkatdlog

    zk_cc = TokenChaincode(
        zkatdlog.new_validator(zk_pp, Deserializer(), device=False),
        ledger, zk_pp.serialize())
    return dict(zk_pp=zk_pp, zk_cc=zk_cc, issuer=issuer, auditor=auditor,
                alice=alice, bob=bob, fab_raw=issue.outputs[0].serialize())


class TestRequestUpgrade:
    def test_upgrade_verb_end_to_end(self, upgrade_world):
        w = upgrade_world
        driver = ZkDlogDriverService(w["zk_pp"], device=False)
        rows = [UnspentToken(id=ID("old1", 0),
                             owner=bytes(w["alice"].identity),
                             type="USD", quantity="0x4d")]
        req = Request("up1", driver)
        action = req.upgrade(rows, bytes(w["bob"].identity),
                             wallet=lambda tid: (w["fab_raw"], None))
        # the assembled transfer carries an upgrade witness for the input
        assert action.inputs[0].upgrade_witness is not None
        assert action.inputs[0].upgrade_witness.quantity == "0x4d"

        wire = req.token_request()
        msg = req.marshal_to_sign()
        wire.auditor_signatures = [w["auditor"].sign(msg)]
        wire.signatures = [w["alice"].sign(msg)]
        res = w["zk_cc"].process_request("up1", wire.to_bytes())
        assert res.status == "VALID", res.message

    def test_upgrade_empty_tokens_rejected(self, upgrade_world):
        driver = ZkDlogDriverService(upgrade_world["zk_pp"], device=False)
        req = Request("up2", driver)
        with pytest.raises(RequestBuilderError, match="empty"):
            req.upgrade([], b"bob")
