"""Live telemetry plane: HTTP exposition endpoints + end-to-end trace
propagation through the serve frontend.

The TelemetryServer binds an ephemeral port per test (config.port=0), so
tests never collide with each other or a real scrape port. The /metrics
validator is a pure-Python walk of the exposition grammar — the
acceptance bar is "a real Prometheus scraper would accept this", checked
without any non-stdlib dependency.
"""

import asyncio
import json
import re
import urllib.error
import urllib.request

import pytest
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from fabric_token_sdk_tpu.obs import (GLOBAL, TRACER, MetricsProvider,
                                      TelemetryConfig, TelemetryServer,
                                      serve_telemetry, spans_to_chrome_trace)
from fabric_token_sdk_tpu.obs.tracing import Tracer
from fabric_token_sdk_tpu.resilience import FaultInjector, ResilienceConfig
from fabric_token_sdk_tpu.serve import (STATUS_OK, ServeConfig,
                                        VerificationService)


def _get(url: str) -> tuple[int, str, str]:
    """(status, content-type, body); 4xx/5xx do not raise."""
    try:
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            return (resp.status, resp.headers.get("Content-Type", ""),
                    resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, err.headers.get("Content-Type", ""), \
            err.read().decode()


# --------------------------------------------------------------- grammar
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram)$")
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'          # metric name
    r'(\{([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})?'  # labels
    r' (NaN|[+-]Inf|[0-9.e+-]+)$')          # value


def validate_prometheus(text: str) -> dict:
    """Walk every line of an exposition body; raises AssertionError on
    any grammar violation. Returns {family: type}."""
    types: dict[str, str] = {}
    helped: set[str] = set()
    assert text.endswith("\n"), "exposition must end with a line feed"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            m = _HELP_RE.match(line)
            assert m, f"malformed HELP line: {line!r}"
            helped.add(m.group(1))
            continue
        if line.startswith("# TYPE "):
            m = _TYPE_RE.match(line)
            assert m, f"malformed TYPE line: {line!r}"
            types[m.group(1)] = m.group(2)
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        base = re.sub(r"_(bucket|sum|count)$", "", m.group(1))
        assert base in types or m.group(1) in types, \
            f"sample before its TYPE: {line!r}"
    assert set(types) == helped, "HELP/TYPE blocks must pair up"
    return types


class _TruthRange:
    def verify(self, proofs, commitments):
        return np.asarray([bool(p) for p in proofs], dtype=bool)


class _TruthZK:
    def __init__(self):
        self._range = _TruthRange()

    def prewarm_shapes(self, batch_sizes=(1,), include_block=True):
        return {b: 0.0 for b in batch_sizes}


def _run_service(svc, n_requests: int = 0, body=None):
    """Start svc, run `body(svc)` (async callable) or submit n truthy
    range requests, stop; returns body's/gather's result."""

    async def run():
        await svc.start()
        if body is not None:
            out = await body(svc)
        else:
            out = await asyncio.gather(*[
                svc.submit_range(True, object(), deadline_s=30.0)
                for _ in range(n_requests)])
        await svc.stop()
        return out

    return asyncio.run(run())


# ------------------------------------------------------------- endpoints
def test_metrics_endpoint_serves_valid_prometheus_text():
    provider = MetricsProvider()
    provider.counter("demo_total", help="Demo counter",
                     path='C:\\x "q"\nend').add(3)
    provider.gauge("demo_gauge", help="Demo gauge").set(float("inf"))
    provider.histogram("demo_seconds", help="Demo histogram").observe(0.01)
    server = TelemetryServer(TelemetryConfig(port=0), provider=provider,
                             tracer=Tracer(provider=provider))
    url = server.start()
    try:
        code, ctype, body = _get(url + "/metrics")
    finally:
        server.stop()
    assert code == 200
    assert ctype.startswith("text/plain")
    types = validate_prometheus(body)
    assert types["demo_total"] == "counter"
    assert types["demo_seconds"] == "histogram"
    assert 'le="+Inf"' in body
    # a scrape observes itself: the response already counts this scrape
    assert re.search(
        r'telemetry_scrapes_total\{endpoint="/metrics"\} 1\.0', body)


def test_index_and_unknown_path():
    server = TelemetryServer(TelemetryConfig(port=0),
                             provider=MetricsProvider())
    url = server.start()
    try:
        code, _, body = _get(url + "/")
        assert code == 200 and "/metrics" in body and "/tracez" in body
        code, _, _ = _get(url + "/nope")
        assert code == 404
    finally:
        server.stop()


def test_healthz_flips_503_when_breaker_forced_open():
    svc = VerificationService(
        _TruthZK(), config=ServeConfig(buckets=(8,), max_wait_s=0.005),
        resilience=ResilienceConfig(retry_base_s=0.0, retry_cap_s=0.0,
                                    watchdog_timeout_s=None))

    async def body(svc):
        server = serve_telemetry(svc, TelemetryConfig(port=0))
        try:
            loop = asyncio.get_running_loop()
            code, _, b = await loop.run_in_executor(
                None, _get, server.url + "/healthz")
            assert code == 200 and b == "ok\n"
            ready_code, _, _ = await loop.run_in_executor(
                None, _get, server.url + "/readyz")
            assert ready_code == 200, "prewarmed + running must be ready"

            svc.breaker.force_open()
            code, ctype, b = await loop.run_in_executor(
                None, _get, server.url + "/healthz")
            assert code == 503 and ctype.startswith("application/json")
            doc = json.loads(b)
            assert doc["status"] == "unavailable"
            assert "breaker" in doc["failures"]

            svc.breaker.force_close()
            code, _, _ = await loop.run_in_executor(
                None, _get, server.url + "/healthz")
            assert code == 200
        finally:
            server.stop()
        return True

    assert _run_service(svc, body=body)


def test_readyz_fails_before_start_and_prewarm():
    svc = VerificationService(
        _TruthZK(), config=ServeConfig(buckets=(8,), max_wait_s=0.005))
    server = serve_telemetry(svc, TelemetryConfig(port=0))
    try:
        code, _, body = _get(server.url + "/readyz")
        assert code == 503
        failures = json.loads(body)["failures"]
        assert "running" in failures and "prewarm" in failures
    finally:
        server.stop()


def test_statusz_valid_json_under_concurrent_scrapes():
    svc = VerificationService(
        _TruthZK(), config=ServeConfig(buckets=(8,), max_wait_s=0.005))

    async def body(svc):
        server = serve_telemetry(svc, TelemetryConfig(port=0))
        loop = asyncio.get_running_loop()

        def scrape(path):
            return _get(server.url + path)

        try:
            await asyncio.gather(*[
                svc.submit_range(True, object(), deadline_s=30.0)
                for _ in range(8)])
            with ThreadPoolExecutor(max_workers=8) as pool:
                futs = [loop.run_in_executor(pool, scrape, path)
                        for _ in range(6)
                        for path in ("/statusz", "/metrics", "/tracez")]
                outs = await asyncio.gather(*futs)
        finally:
            server.stop()
        return outs

    outs = _run_service(svc, body=body)
    assert len(outs) == 18
    for code, ctype, text in outs:
        assert code == 200
        if ctype.startswith("application/json"):
            json.loads(text)
    status = next(json.loads(t) for c, ct, t in outs
                  if ct.startswith("application/json") and '"serve"' in t)
    assert status["serve"]["running"] is True
    assert status["serve"]["prewarm"]["ready"] == [8]
    assert "pipeline" in status and "profile" in status
    assert status["uptime_s"] >= 0


def test_tracez_exports_chrome_trace_json():
    provider = MetricsProvider()
    tracer = Tracer(provider=provider)
    with tracer.span("outer"):
        with tracer.span("inner"):
            pass
    server = TelemetryServer(TelemetryConfig(port=0), provider=provider,
                             tracer=tracer)
    url = server.start()
    try:
        code, ctype, body = _get(url + "/tracez")
    finally:
        server.stop()
    assert code == 200 and ctype.startswith("application/json")
    doc = json.loads(body)
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"outer", "inner"} <= names


def test_tenantz_reports_per_tenant_table():
    from fabric_token_sdk_tpu.obs import TenantSloMonitor, TenantSloPolicy
    monitor = TenantSloMonitor(policy=TenantSloPolicy(min_volume=4),
                               provider=MetricsProvider())
    svc = VerificationService(
        _TruthZK(), config=ServeConfig(buckets=(8,), max_wait_s=0.005),
        tenant_slo=monitor)

    async def body(svc):
        server = serve_telemetry(svc, TelemetryConfig(port=0))
        loop = asyncio.get_running_loop()
        try:
            await asyncio.gather(*[
                svc.submit_range(True, object(), deadline_s=30.0, tenant=t)
                for t in ("alpha", "beta") for _ in range(4)])
            tenantz = await loop.run_in_executor(
                None, _get, server.url + "/tenantz")
            statusz = await loop.run_in_executor(
                None, _get, server.url + "/statusz")
        finally:
            server.stop()
        return tenantz, statusz

    (code, ctype, text), (s_code, _, s_text) = _run_service(svc, body=body)
    assert code == 200 and ctype.startswith("application/json")
    doc = json.loads(text)
    assert doc["enabled"] is True
    assert doc["shed_policy_enabled"] is True
    for t in ("alpha", "beta"):
        row = doc["tenants"][t]
        assert row["requests"] == 4
        assert row["availability"] == 1.0
        assert row["sheds"] == 0 and row["fast_burn_active"] is False
        assert row["budget_remaining"] == 1.0
        assert set(row["burn_rate"]) == {"60s", "300s"}
        # joined with the live scheduler/in-flight view, drained by now
        assert row["queued"] == 0 and row["inflight"] == 0
    assert set(doc["fairness"]) == {"throughput", "p99"}
    # the same table rides along as the "tenants" key of /statusz
    assert s_code == 200
    assert json.loads(s_text)["tenants"]["tenants"]["alpha"]["requests"] == 4


def test_tenantz_disabled_without_monitor():
    svc = VerificationService(
        _TruthZK(), config=ServeConfig(buckets=(8,), max_wait_s=0.005))

    async def body(svc):
        server = serve_telemetry(svc, TelemetryConfig(port=0))
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                None, _get, server.url + "/tenantz")
        finally:
            server.stop()

    code, ctype, text = _run_service(svc, body=body)
    assert code == 200 and ctype.startswith("application/json")
    assert json.loads(text) == {"enabled": False}


# ----------------------------------------------------- trace propagation
def test_serve_request_trace_is_a_connected_chain():
    """Acceptance: a sampled request's exported trace shows admission ->
    queue wait -> batch dispatch (shared span, linked) -> verdict, with
    retry spans under the same batch span."""
    GLOBAL.reset()
    TRACER.clear()
    inj = FaultInjector(seed=0, schedule={0: "transient"},
                        sleep=lambda s: None)
    svc = VerificationService(
        inj.wrap(_TruthZK()),
        config=ServeConfig(buckets=(8,), max_wait_s=0.005, trace_every=1),
        resilience=ResilienceConfig(retry_attempts=3, retry_base_s=0.0,
                                    retry_cap_s=0.0,
                                    breaker_min_volume=10_000,
                                    watchdog_timeout_s=None))
    results = _run_service(svc, n_requests=4)
    assert [r.status for r in results] == [STATUS_OK] * 4
    assert inj.injected["transient"] == 1

    roots = TRACER.root_snapshot()
    req_roots = [r for r in roots if r.name == "serve.request"]
    batch_roots = [r for r in roots if r.name == "serve.batch"]
    assert len(req_roots) == 4 and batch_roots

    batch_ids = {b.span_id: b for b in batch_roots}
    for req in req_roots:
        # each request is its own trace, closed with its verdict
        assert req.parent_id is None and req.duration is not None
        assert req.attributes["status"] == "ok"
        assert [e[0] for e in req.events][0] == "admitted"
        assert "verdict" in [e[0] for e in req.events]
        # queue wait reconstructed as a child at dispatch time
        assert [c.name for c in req.children] == ["serve.queue_wait"]
        # linked (not parented) to the shared batch span, bidirectionally
        batch_links = [l for l in req.links if l["role"] == "batch"]
        assert len(batch_links) == 1
        batch = batch_ids[batch_links[0]["span_id"]]
        assert req.span_id in {l["span_id"] for l in batch.links
                               if l["role"] == "member"}
        assert req.trace_id != batch.trace_id

    # the retried dispatch: retry span and both attempts under ONE batch
    retried = [b for b in batch_roots
               if "resil.retry" in {c.name for c in b.children}]
    assert len(retried) == 1
    child_names = [c.name for c in retried[0].children]
    assert child_names.count("serve.dispatch") == 2
    assert retried[0].attributes["served_by"] == "device"

    # links survive the Chrome-trace export on both sides of the join
    doc = json.loads(json.dumps(spans_to_chrome_trace(roots)))
    by_id = {e["args"]["span_id"]: e
             for e in doc["traceEvents"] if e["ph"] == "X"}
    exported_req = by_id[req_roots[0].span_id]
    link = next(l for l in exported_req["args"]["links"]
                if l["role"] == "batch")
    assert by_id[link["span_id"]]["name"] == "serve.batch"
    assert req_roots[0].span_id in {
        l["span_id"] for l in by_id[link["span_id"]]["args"]["links"]}


def test_trace_every_zero_disables_request_spans():
    GLOBAL.reset()
    TRACER.clear()
    svc = VerificationService(
        _TruthZK(),
        config=ServeConfig(buckets=(8,), max_wait_s=0.005, trace_every=0))
    results = _run_service(svc, n_requests=4)
    assert all(r.ok for r in results)
    assert not [r for r in TRACER.root_snapshot()
                if r.name == "serve.request"]


@pytest.mark.crash
def test_server_socket_reuses_address():
    """Restart-friendliness: a supervisor-respawned telemetry plane must
    rebind its fixed scrape port immediately (SO_REUSEADDR), not
    crash-loop on EADDRINUSE through the predecessor's TIME_WAIT."""
    import socket

    from fabric_token_sdk_tpu.obs.telemetry import _TelemetryHTTPServer

    assert _TelemetryHTTPServer.allow_reuse_address is True

    provider = MetricsProvider()
    server = TelemetryServer(TelemetryConfig(port=0), provider=provider,
                             tracer=Tracer(provider=provider))
    url = server.start()
    port = server.port
    try:
        assert server._httpd.socket.getsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR) != 0
        # an accepted connection leaves sockets behind in TIME_WAIT
        assert _get(url + "/metrics")[0] == 200
    finally:
        server.stop()

    succ = TelemetryServer(TelemetryConfig(port=port), provider=provider,
                           tracer=Tracer(provider=provider))
    succ.start()                       # immediate same-port rebind
    try:
        assert _get(succ.url + "/metrics")[0] == 200
    finally:
        succ.stop()
