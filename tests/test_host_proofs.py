"""Round-trip + tamper tests for the host proof layer (oracle).

Mirrors the reference unit test strategy (SURVEY.md §4: ginkgo suites in
crypto/rp, crypto/transfer, crypto/issue do prove/verify round trips and
tamper checks)."""

import pytest

from fabric_token_sdk_tpu.crypto import bn254, rp, setup as setup_mod
from fabric_token_sdk_tpu.crypto import issue_proof, token_commit, transfer_proof
from fabric_token_sdk_tpu.crypto.bn254 import fr_rand, fr_sub, g1_add, g1_mul, g1_neg
from fabric_token_sdk_tpu.crypto.rp import ProofError


@pytest.fixture(scope="module")
def pp16():
    return setup_mod.setup(16)


def _value_commitment(pp, value, bf):
    # com = G^v H^bf with (G, H) = PedersenGenerators[1:]
    gens = pp.pedersen_generators
    return g1_add(g1_mul(gens[1], value), g1_mul(gens[2], bf))


class TestRangeProof:
    def test_roundtrip_accept(self, pp16):
        rpp = pp16.range_proof_params
        bf = fr_rand()
        com = _value_commitment(pp16, 250, bf)
        proof = rp.range_prove(com, 250, pp16.pedersen_generators[1:], bf,
                               rpp.left_generators, rpp.right_generators,
                               rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        rp.range_verify(proof, com, pp16.pedersen_generators[1:],
                        rpp.left_generators, rpp.right_generators,
                        rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)

    def test_serialization_roundtrip(self, pp16):
        rpp = pp16.range_proof_params
        bf = fr_rand()
        com = _value_commitment(pp16, 77, bf)
        proof = rp.range_prove(com, 77, pp16.pedersen_generators[1:], bf,
                               rpp.left_generators, rpp.right_generators,
                               rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        raw = proof.serialize()
        restored = rp.RangeProof.deserialize(raw)
        assert restored.serialize() == raw
        rp.range_verify(restored, com, pp16.pedersen_generators[1:],
                        rpp.left_generators, rpp.right_generators,
                        rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)

    def test_out_of_range_value_rejected(self, pp16):
        # prove with a value that exceeds 2^16 - the bit decomposition
        # truncates, so the outer polynomial check must fail
        rpp = pp16.range_proof_params
        bf = fr_rand()
        value = (1 << 16) + 5
        com = _value_commitment(pp16, value, bf)
        proof = rp.range_prove(com, value, pp16.pedersen_generators[1:], bf,
                               rpp.left_generators, rpp.right_generators,
                               rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        with pytest.raises(ProofError, match="invalid range proof"):
            rp.range_verify(proof, com, pp16.pedersen_generators[1:],
                            rpp.left_generators, rpp.right_generators,
                            rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)

    def test_tampered_proof_rejected(self, pp16):
        rpp = pp16.range_proof_params
        bf = fr_rand()
        com = _value_commitment(pp16, 33, bf)
        proof = rp.range_prove(com, 33, pp16.pedersen_generators[1:], bf,
                               rpp.left_generators, rpp.right_generators,
                               rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        proof.data.tau = fr_rand()
        with pytest.raises(ProofError):
            rp.range_verify(proof, com, pp16.pedersen_generators[1:],
                            rpp.left_generators, rpp.right_generators,
                            rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)

    def test_wrong_commitment_rejected(self, pp16):
        rpp = pp16.range_proof_params
        bf = fr_rand()
        com = _value_commitment(pp16, 33, bf)
        other = _value_commitment(pp16, 34, bf)
        proof = rp.range_prove(com, 33, pp16.pedersen_generators[1:], bf,
                               rpp.left_generators, rpp.right_generators,
                               rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        with pytest.raises(ProofError):
            rp.range_verify(proof, other, pp16.pedersen_generators[1:],
                            rpp.left_generators, rpp.right_generators,
                            rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)

    def test_tampered_ipa_rejected(self, pp16):
        rpp = pp16.range_proof_params
        bf = fr_rand()
        com = _value_commitment(pp16, 100, bf)
        proof = rp.range_prove(com, 100, pp16.pedersen_generators[1:], bf,
                               rpp.left_generators, rpp.right_generators,
                               rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)
        proof.ipa.left = fr_rand()
        with pytest.raises(ProofError, match="invalid IPA"):
            rp.range_verify(proof, com, pp16.pedersen_generators[1:],
                            rpp.left_generators, rpp.right_generators,
                            rpp.P, rpp.Q, rpp.number_of_rounds, rpp.bit_length)


class TestTransferProof:
    def _make_transfer(self, pp, in_vals, out_vals, tamper_out_value=None):
        token_type = "USD"
        in_tokens, in_w = token_commit.get_tokens_with_witness(
            in_vals, token_type, pp.pedersen_generators)
        out_tokens, out_w = token_commit.get_tokens_with_witness(
            out_vals, token_type, pp.pedersen_generators)
        if tamper_out_value is not None:
            # change a committed output value without updating the witness sum
            out_w[0].value = tamper_out_value
            out_tokens[0] = token_commit.commit_token(
                token_type, tamper_out_value, out_w[0].blinding_factor,
                pp.pedersen_generators)
        proof = transfer_proof.transfer_prove(
            [w.as_tuple() for w in in_w], [w.as_tuple() for w in out_w],
            in_tokens, out_tokens, pp)
        return proof, in_tokens, out_tokens

    def test_two_in_two_out_accept(self, pp16):
        proof, ins, outs = self._make_transfer(pp16, [40, 60], [30, 70])
        transfer_proof.transfer_verify(proof, ins, outs, pp16)

    def test_one_in_one_out_skips_range(self, pp16):
        proof, ins, outs = self._make_transfer(pp16, [50], [50])
        parsed = transfer_proof.TransferProof.deserialize(proof)
        assert parsed.range_correctness is None or not parsed.range_correctness.proofs
        transfer_proof.transfer_verify(proof, ins, outs, pp16)

    def test_unbalanced_rejected(self, pp16):
        proof, ins, outs = self._make_transfer(pp16, [40, 60], [30, 71])
        with pytest.raises(ProofError, match="invalid transfer proof"):
            transfer_proof.transfer_verify(proof, ins, outs, pp16)

    def test_swapped_statement_rejected(self, pp16):
        proof, ins, outs = self._make_transfer(pp16, [40, 60], [30, 70])
        with pytest.raises(ProofError):
            transfer_proof.transfer_verify(proof, outs, ins, pp16)


class TestIssueProof:
    def test_roundtrip_accept(self, pp16):
        tokens, w = token_commit.get_tokens_with_witness(
            [10, 20], "EUR", pp16.pedersen_generators)
        proof = issue_proof.issue_prove([x.as_tuple() for x in w], tokens, pp16)
        issue_proof.issue_verify(proof, tokens, pp16)

    def test_out_of_range_issue_rejected(self, pp16):
        value = (1 << 16) + 1
        tokens, w = token_commit.get_tokens_with_witness(
            [value], "EUR", pp16.pedersen_generators)
        proof = issue_proof.issue_prove([x.as_tuple() for x in w], tokens, pp16)
        with pytest.raises(ProofError, match="invalid issue proof"):
            issue_proof.issue_verify(proof, tokens, pp16)

    def test_wrong_tokens_rejected(self, pp16):
        tokens, w = token_commit.get_tokens_with_witness(
            [10, 20], "EUR", pp16.pedersen_generators)
        other, _ = token_commit.get_tokens_with_witness(
            [10, 20], "EUR", pp16.pedersen_generators)
        proof = issue_proof.issue_prove([x.as_tuple() for x in w], tokens, pp16)
        with pytest.raises(ProofError):
            issue_proof.issue_verify(proof, other, pp16)


class TestAuditReopen:
    def test_reopen_accept_and_reject(self, pp16):
        bf = fr_rand()
        data = token_commit.commit_token("USD", 42, bf, pp16.pedersen_generators)
        token_commit.audit_inspect_output(data, "USD", 42, bf,
                                          pp16.pedersen_generators)
        with pytest.raises(token_commit.TokenError):
            token_commit.audit_inspect_output(data, "USD", 43, bf,
                                              pp16.pedersen_generators)


class TestPublicParams:
    def test_setup_validate_roundtrip(self, pp16):
        pp16.validate()
        raw = pp16.serialize()
        restored = setup_mod.PublicParams.deserialize(raw)
        restored.validate()
        assert restored.serialize() == raw
        assert restored.max_token == (1 << 16) - 1
        assert restored.range_proof_params.number_of_rounds == 4
        assert restored.pedersen_generators == pp16.pedersen_generators

    def test_unsupported_precision_rejected(self):
        pp = setup_mod.setup(8)
        with pytest.raises(setup_mod.SetupError, match="invalid bit length"):
            pp.validate()
