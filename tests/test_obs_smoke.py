"""Tier-1 observability smoke: a small txgen load on CPU must light up
every layer of the instrumentation — node lifecycle counters, tcc phase
histograms, selector/db latencies, txgen op counters — and the resulting
registry must export as conformant Prometheus text and roll up into the
BENCH-style JSON report.

The metric family names asserted here are a stable interface (see
ROADMAP.md): dashboards and the bench harness key on them, so renaming a
family is a breaking change this test is meant to catch.
"""

import json
import re

import pytest

pytest.importorskip("cryptography")

from fabric_token_sdk_tpu.core import fabtoken
from fabric_token_sdk_tpu.harness.txgen import LoadGenerator
from fabric_token_sdk_tpu.obs import GLOBAL, TRACER
from fabric_token_sdk_tpu.services.auditor import AuditorNode
from fabric_token_sdk_tpu.services.identity.deserializer import Deserializer
from fabric_token_sdk_tpu.services.identity.x509 import new_signing_identity
from fabric_token_sdk_tpu.services.network.tcc import MemoryLedger, \
    TokenChaincode
from fabric_token_sdk_tpu.services.node import TokenNode
from fabric_token_sdk_tpu.services.ttx import SessionBus

# families every successful load must populate, per layer
EXPECTED_COUNTERS = (
    "ttx_executions_total",      # node lifecycle
    "ttx_commits_total",         # finality ingestion
    "tcc_requests_total",        # chaincode entry point
    "txgen_ops_total",           # harness
)
EXPECTED_HISTOGRAMS = (
    "ttx_execute_seconds",
    "ttx_collect_endorsements_seconds",
    "ttx_ordering_finality_seconds",
    "ttx_commit_ingest_seconds",
    "tcc_process_request_seconds",
    "tcc_validate_seconds",
    "tcc_translate_seconds",
    "tcc_commit_seconds",
    "selector_select_seconds",
    "db_store_token_seconds",
    "txgen_op_seconds",
)


@pytest.fixture
def net():
    GLOBAL.reset()
    TRACER.clear()
    issuer_keys = new_signing_identity()
    auditor_keys = new_signing_identity()
    pp = fabtoken.setup(64)
    pp.issuer_ids = [issuer_keys.identity]
    pp.auditor = bytes(auditor_keys.identity)
    cc = TokenChaincode(fabtoken.new_validator(pp, Deserializer()),
                        MemoryLedger(), pp.serialize())
    bus = SessionBus()
    TokenNode("issuer", issuer_keys, bus, cc, auditor_name="auditor")
    AuditorNode("auditor", auditor_keys, bus, cc, auditor_name="auditor")
    users = [TokenNode(n, new_signing_identity(), bus, cc,
                       auditor_name="auditor") for n in ("alice", "bob")]
    return users


def _family_totals(provider):
    totals: dict[str, float] = {}
    counts: dict[str, int] = {}
    for (name, _labels), val in provider.snapshot().items():
        if isinstance(val, (int, float)):
            totals[name] = totals.get(name, 0.0) + val
        else:  # histogram snapshot dict
            counts[name] = counts.get(name, 0) + val["count"]
    return totals, counts


def test_txgen_load_populates_all_layers(net):
    report = LoadGenerator(net, "issuer", seed=3).run(12, bootstrap_value=200)
    assert report.succeeded > 0, report.failures_by_error()

    totals, counts = _family_totals(GLOBAL)
    for fam in EXPECTED_COUNTERS:
        assert totals.get(fam, 0) > 0, f"counter family silent: {fam}"
    for fam in EXPECTED_HISTOGRAMS:
        assert counts.get(fam, 0) > 0, f"histogram family silent: {fam}"

    # the span tracer saw the ttx -> tcc call chain as one tree
    root = TRACER.last_root("ttx.execute")
    assert root is not None
    names = {s.name for s in root.walk()}
    assert {"ttx.collect_endorsements", "ttx.ordering_and_finality",
            "tcc.process_request", "tcc.validate", "tcc.translate",
            "tcc.commit"} <= names


def test_node_scoped_exposition_and_bench_report(net):
    report = LoadGenerator(net, "issuer", seed=4).run(8, bootstrap_value=100)
    assert report.succeeded > 0

    # per-node scrape carries the node label and stays conformant
    text = net[0].prometheus_text()
    assert re.search(r'ttx_executions_total\{[^}]*node="alice"', text)
    assert "# TYPE ttx_execute_seconds histogram" in text
    assert 'le="+Inf"' in text

    # rolled-up BENCH report: JSON-serializable, families present
    doc = report.bench_report(extra={"scenario": "smoke"})
    doc = json.loads(json.dumps(doc))
    assert doc["schema"] == "fts-obs-bench-v1"
    assert doc["txgen"]["succeeded"] == report.succeeded
    assert doc["scenario"] == "smoke"
    assert "ttx_executions_total" in doc["counters"]
    lat = doc["histograms"]["ttx_execute_seconds"][0]
    assert lat["count"] > 0 and lat["p95"] >= lat["p50"] > 0


# serve/ frontend families — stable interface like the layers above
# (ROADMAP stable-metric-names; also asserted device-side in
# tests/test_serve_smoke.py, which runs without cryptography)
EXPECTED_SERVE_FAMILIES = (
    "serve_requests_total",
    "serve_results_total",
    "serve_batches_total",
    "serve_queue_depth",
    "serve_batch_fill_ratio",
    "serve_batch_rows",
    "serve_wait_seconds",
    "serve_dispatch_seconds",
)


def test_serve_family_stable_names():
    import asyncio

    import numpy as np

    from fabric_token_sdk_tpu.serve import ServeConfig, VerificationService

    class _FakeRange:
        def verify(self, proofs, commitments):
            return np.ones(len(proofs), dtype=bool)

    class _FakeZK:
        _range = _FakeRange()

    GLOBAL.reset()
    svc = VerificationService(
        _FakeZK(), config=ServeConfig(buckets=(4,), max_wait_s=0.001))

    async def run():
        await svc.start(prewarm=False)
        out = await asyncio.gather(*[
            svc.submit_range(object(), object()) for _ in range(6)])
        await svc.stop()
        return out

    results = asyncio.run(run())
    assert all(r.ok for r in results)
    text = GLOBAL.prometheus_text()
    for fam in EXPECTED_SERVE_FAMILIES:
        assert fam in text, f"serve family silent: {fam}"
    assert "# TYPE serve_queue_depth gauge" in text


# serve/ per-device dispatch lanes (multi-chip continuous batching) —
# stable interface; every sample carries a lane="<index>" label
EXPECTED_LANE_FAMILIES = (
    "lane_dispatch_total",
    "lane_rows_total",
    "lane_busy_seconds",
    "lane_inflight",
)


def test_lane_family_stable_names_multi_lane():
    """Drive a 2-lane service with a slow (blocking) verifier so the
    dispatch loop overlaps batches across lanes, then assert every
    lane_* family exports with per-lane labels. describe() alone does
    not render a family — the instruments must actually fire."""
    import asyncio
    import time

    import numpy as np

    from fabric_token_sdk_tpu.serve import ServeConfig, VerificationService

    class _SlowRange:
        def verify(self, proofs, commitments):
            time.sleep(0.05)          # hold the lane busy -> overlap
            return np.ones(len(proofs), dtype=bool)

    class _FakeZK:
        _range = _SlowRange()

    GLOBAL.reset()
    svc = VerificationService(
        _FakeZK(),
        config=ServeConfig(buckets=(4,), max_wait_s=0.001, n_lanes=2))

    async def run():
        await svc.start(prewarm=False)
        out = await asyncio.gather(*[
            svc.submit_range(object(), object()) for _ in range(12)])
        await svc.stop()
        return out

    results = asyncio.run(run())
    assert all(r.ok for r in results)
    lanes_used = {r.device_lane for r in results}
    assert lanes_used == {0, 1}, lanes_used
    text = GLOBAL.prometheus_text()
    for fam in EXPECTED_LANE_FAMILIES:
        assert fam in text, f"lane family silent: {fam}"
    for lane in (0, 1):
        assert re.search(r'lane_dispatch_total\{[^}]*lane="%d"' % lane,
                         text), (lane, text)
    assert "# TYPE lane_inflight gauge" in text
    # lane bookkeeping rolled up in status()
    st = svc.status()
    assert len(st["lanes"]) == 2
    assert sum(l["dispatches"] for l in st["lanes"]) >= 2
    assert sum(l["rows"] for l in st["lanes"]) == 12


# live telemetry plane families (PR: telemetry) — stable interface; the
# endpoint behaviour itself is covered crypto-free in tests/test_telemetry.py
EXPECTED_TELEMETRY_FAMILIES = (
    "telemetry_scrapes_total",
    "telemetry_scrape_seconds",
    "slo_availability_ratio",
    "slo_p99_seconds",
    "slo_error_budget_burn_rate",
    "slo_window_requests",
    "slo_fast_burn_active",
    "profile_compile_seconds",
    "profile_compile_cache_total",
)


def test_live_telemetry_slo_profile_families_export():
    """One scrape through the real HTTP plane lights every telemetry_*,
    slo_* and profile_* family a CPU-only run can light."""
    import urllib.request

    from fabric_token_sdk_tpu.obs import (PROFILER, SloMonitor,
                                          TelemetryConfig, TelemetryServer)

    GLOBAL.reset()
    slo = SloMonitor()
    slo.record(True, latency_s=0.01)
    slo.record(False)
    PROFILER.record_compile("smoke", 16, 0.5)
    PROFILER.record_cache_event("smoke", hit=True)
    server = TelemetryServer(TelemetryConfig(port=0))
    url = server.start()
    try:
        # two scrapes: telemetry_scrape_seconds observes after rendering,
        # so only the second body can carry the first scrape's latency
        for _ in range(2):
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=10.0) as resp:
                text = resp.read().decode()
    finally:
        server.stop()
    for fam in EXPECTED_TELEMETRY_FAMILIES:
        assert fam in text, f"telemetry family silent: {fam}"
    assert "# TYPE slo_availability_ratio gauge" in text
    assert re.search(
        r'telemetry_scrapes_total\{endpoint="/metrics"\} 2\.0', text)


# per-tenant SLO plane families (PR: per-tenant SLO) — stable interface;
# behaviour is covered crypto-free in tests/test_slo.py and
# tests/test_tenant_slo.py
EXPECTED_TENANT_SLO_FAMILIES = (
    "slo_tenant_availability",
    "slo_tenant_p99_seconds",
    "slo_tenant_burn_rate",
    "slo_tenant_budget_remaining",
    "slo_tenant_evictions_total",
    "slo_fairness_index",
    "serve_tenant_queue_seconds",
    "serve_tenant_e2e_seconds",
    "serve_tenant_sheds_total",
)


def test_tenant_slo_families_export():
    """One tripped tenant, one served tenant and one LRU eviction light
    every per-tenant SLO family in a single exposition."""
    import asyncio

    import numpy as np

    from fabric_token_sdk_tpu.obs import TenantSloMonitor, TenantSloPolicy
    from fabric_token_sdk_tpu.serve import ServeConfig, VerificationService

    class _FakeRange:
        def verify(self, proofs, commitments):
            return np.ones(len(proofs), dtype=bool)

    class _FakeZK:
        _range = _FakeRange()

    GLOBAL.reset()
    clk = {"t": 1000.0}
    monitor = TenantSloMonitor(
        policy=TenantSloPolicy(min_volume=4, max_tenants=2),
        clock=lambda: clk["t"])
    monitor.record("filler", True, 0.01)     # LRU fodder: evicted below
    for _ in range(8):                       # trip "hot": 100% failures
        monitor.record("hot", False)
        clk["t"] += 0.01
    assert monitor.shedding("hot")
    svc = VerificationService(
        _FakeZK(), config=ServeConfig(buckets=(4,), max_wait_s=0.001),
        tenant_slo=monitor)

    async def run():
        await svc.start(prewarm=False)
        shed = await svc.submit_range(object(), object(), tenant="hot")
        ok = await svc.submit_range(object(), object(), tenant="victim")
        await svc.stop()
        return shed, ok

    shed, ok = asyncio.run(run())
    assert shed.status == "shed_tenant_slo" and ok.ok
    # the victim's arrival made three tenants: "filler" was LRU-evicted
    assert monitor.evictions >= 1 and "filler" not in monitor.tenants()

    text = GLOBAL.prometheus_text()
    for fam in EXPECTED_TENANT_SLO_FAMILIES:
        assert fam in text, f"tenant slo family silent: {fam}"
    assert "# TYPE slo_tenant_burn_rate gauge" in text
    assert "# TYPE serve_tenant_e2e_seconds histogram" in text
    assert re.search(r'slo_fairness_index\{basis="throughput"\}', text)
    assert re.search(r'serve_tenant_sheds_total\{[^}]*tms_id="hot"', text)


# flight recorder / heartbeat / fleet federation families (PR:
# observability) — stable interface; behaviour is covered crypto-free in
# tests/test_journal.py, test_heartbeat.py and test_aggregate.py
EXPECTED_FLIGHT_FAMILIES = (
    "journal_events_total",
    "journal_dropped_total",
    "journal_incidents_total",
    "hb_beats_total",
    "hb_last_age_seconds",
    "hb_stalls_total",
    "fleet_nodes",
    "fleet_samples",
    "fleet_merge_conflicts_total",
    "fleet_node_age_seconds",
)


def test_flight_recorder_and_fleet_families_export(tmp_path):
    """One pass through journal + heartbeat + federation lights every
    journal_*, hb_* and fleet_* family in a single exposition."""
    from fabric_token_sdk_tpu.obs import (FleetAggregator, Heartbeat,
                                          Journal, SpoolPublisher,
                                          StallDetector)

    GLOBAL.reset()
    j = Journal(capacity=2, provider=GLOBAL, min_interval_s=0.0)
    j.configure(tmp_path / "flight")
    for i in range(4):                   # wraps the 2-deep ring: drops
        j.record("heartbeat", i=i)
    j.incident("smoke")
    hb = Heartbeat(provider=GLOBAL, journal=j, clock=lambda: 50.0)
    hb.beat("phase_a")
    det = StallDetector(hb.last, default_deadline_s=1.0, grace_s=0.0,
                        provider=GLOBAL, clock=lambda: 100.0)
    assert det.check() == ("phase_a", 50.0)
    spool = tmp_path / "spool"
    SpoolPublisher(spool, "n0", provider=GLOBAL).publish()
    # a node-label collision forces fleet_merge_conflicts_total to light
    (spool / "n1.prom").write_text(
        '# TYPE f counter\nf{node="inner"} 1.0\n')
    text = FleetAggregator(spool, provider=GLOBAL).collect()
    for fam in EXPECTED_FLIGHT_FAMILIES:
        assert fam in text, f"flight family silent: {fam}"
    assert 'node="n0"' in text


# crash-recovery families (PR: crash recovery) — stable interface;
# behaviour is covered crypto-free in tests/test_wal.py and
# tests/test_supervisor.py
EXPECTED_WAL_FAMILIES = (
    "wal_appends_total",
    "wal_bytes_written_total",
    "wal_compactions_total",
    "wal_open_requests",
    "wal_recovery_seconds",
    "wal_replayed_total",
    "wal_segments_total",
    "wal_torn_records_total",
)
EXPECTED_CRASH_FAMILIES = (
    "crash_child_up",
    "crash_escalations_total",
    "crash_failures_total",
    "crash_injected_signals_total",
    "crash_restarts_total",
    "crash_rto_seconds",
)


@pytest.mark.crash
def test_wal_and_crash_families_export(tmp_path):
    """One WAL crash/replay cycle (with a torn tail), one fake-clock
    supervision ladder and one kill-schedule injection light every
    wal_* and crash_* family in a single exposition."""
    import asyncio
    import subprocess
    import sys

    from fabric_token_sdk_tpu.resilience import (ChildSpec, KillSchedule,
                                                 Supervisor,
                                                 SupervisorPolicy)
    from fabric_token_sdk_tpu.serve import (ServeConfig,
                                            VerificationService,
                                            WriteAheadLog)

    GLOBAL.reset()

    class _TruthyRange:
        def verify(self, proofs, coms):
            del coms
            return [bool(p) for p in proofs]

    class _TruthyZK:
        _range = _TruthyRange()

    # -- wal_*: admit under load, crash, tear the tail, recover + replay
    wal = WriteAheadLog(tmp_path / "wal")
    svc = VerificationService(
        _TruthyZK(), config=ServeConfig(buckets=(64,), max_wait_s=3600.0,
                                        default_deadline_s=3600.0),
        wal=wal)

    async def crash():
        await svc.start(prewarm=False)
        tasks = [asyncio.ensure_future(svc.submit_range(True, "c"))
                 for _ in range(3)]
        await asyncio.sleep(0.05)
        await svc.abort()
        for t in tasks:
            t.cancel()

    asyncio.run(crash())
    wal.close()
    [seg] = list((tmp_path / "wal").glob("wal-*.jsonl"))
    with open(seg, "ab") as f:
        f.write(b'{"t":"resolve","id":1')       # torn final record

    succ = VerificationService(
        _TruthyZK(), config=ServeConfig(buckets=(4,), max_wait_s=0.001),
        wal=WriteAheadLog(tmp_path / "wal"))

    async def recover():
        await succ.start(prewarm=False)          # recovery + replay
        await succ.stop(timeout_s=10.0)

    asyncio.run(recover())

    # -- crash_* (ladder): one exit -> cold restart -> liveness RTO
    class _Handle:
        def __init__(self):
            self.pid = 4_194_313                 # past pid_max: unpokable
            self.exitcode = None

        def is_alive(self):
            return self.exitcode is None

        def terminate(self):
            self.exitcode = -15

        def kill(self):
            self.exitcode = -9

        def join(self, timeout=None):
            pass

    clk = {"t": 0.0}
    sup = Supervisor(policy=SupervisorPolicy(backoff_base_s=0.01,
                                             backoff_cap_s=0.02,
                                             cold_after=0),
                     clock=lambda: clk["t"])
    h0 = _Handle()
    sup.add_child(ChildSpec("w", start=lambda ctx: _Handle()), handle=h0)
    h0.exitcode = 1
    sup.poll(1.0)                                # failure + escalation
    sup.poll(2.0)                                # cold restart
    sup.poll(3.0)                                # recovery: RTO observed

    # -- crash_injected_signals_total: one scheduled SIGKILL delivered
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        ks = KillSchedule(seed=2, duration_s=0.2, kills=1, stops=0)
        ks.start(lambda: proc.pid)
        ks.join(timeout_s=10.0)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()

    text = GLOBAL.prometheus_text()
    for fam in EXPECTED_WAL_FAMILIES:
        assert fam in text, f"wal family silent: {fam}"
    for fam in EXPECTED_CRASH_FAMILIES:
        assert fam in text, f"crash family silent: {fam}"
    assert "# TYPE wal_open_requests gauge" in text
    assert "# TYPE crash_rto_seconds histogram" in text


# network front door families (PR: rpc sidecar) — stable interface; the
# protocol behaviour itself is covered crypto-free in tests/test_rpc.py
EXPECTED_RPC_FAMILIES = (
    "rpc_connections_total",
    "rpc_connections_active",
    "rpc_frames_total",
    "rpc_frame_errors_total",
    "rpc_requests_total",
    "rpc_credits",
    "rpc_credit_waits_total",
    "rpc_redials_total",
    "rpc_goaways_total",
    "rpc_deadline_expired_total",
    "rpc_call_seconds",
    "rpc_hedges_total",
    # columnar batch ingest (PR: zero-copy front door)
    "rpc_batch_frames_total",
    "rpc_batch_rows_total",
    "rpc_batch_bytes_total",
    "rpc_decode_seconds",
    "rpc_tenant_deficit",
    # C10k front door (PR: loop sharding + columnar result egress)
    "rpc_loops",
    "rpc_conns",
    "rpc_wakeups_total",
    "rpc_result_batch_frames_total",
    "rpc_result_batch_rows_total",
    "rpc_result_batch_bytes_total",
    "rpc_accept_shed_total",
)


def test_rpc_families_export():
    """One server lifetime lights every rpc_* family: a round-trip, a
    columnar batch frame, a hedged interactive call, a poisoned frame,
    an expired deadline, a credit stall, and a draining GOAWAY stop."""
    import asyncio
    import socket
    import threading
    import time

    from fabric_token_sdk_tpu.serve import (RpcClient, RpcConfig, RpcServer,
                                            ServeConfig, StubZK,
                                            VerificationService,
                                            WorkerUnavailable)
    from fabric_token_sdk_tpu.serve.config import LANE_INTERACTIVE

    GLOBAL.reset()
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()

    def run(coro):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(30.0)

    async def boot():
        svc = VerificationService(
            StubZK(), ServeConfig(buckets=(8,), max_wait_s=0.002))
        await svc.start(prewarm=False)
        server = RpcServer(svc, RpcConfig(conn_credits=2))
        addr = await server.start()
        return svc, server, addr

    svc, server, addr = run(boot())
    cli = RpcClient(addr, call_timeout_s=20.0, credit_wait_s=0.2,
                    hedge_after_s=0.0)
    try:
        assert cli.submit_range([True], [None]).tolist() == [True]
        # one columnar SUBMIT_BATCH frame: two rows share one frame,
        # one admission, one DRR drain burst (lights the batch + tenant
        # families on both roles)
        assert cli.submit_range_batch(
            [True, False], [None, None]).tolist() == [True, False]
        cli.submit_range([True], [None], lane=LANE_INTERACTIVE)  # hedges

        try:  # 5 rows > 2-credit grant: counted stall, then shed
            cli.submit_range([True] * 5, [None] * 5)
        except WorkerUnavailable:
            pass

        cli.clock_offset_s = -30.0  # skew the wire deadline into the past
        try:
            cli.submit_range([True], [None], deadline_s=5.0)
        except WorkerUnavailable:
            pass
        cli.clock_offset_s = 0.0

        poison = socket.create_connection(addr, timeout=5.0)
        poison.sendall(b"\x00" * 12)  # bad magic
        poison.close()
        deadline = time.monotonic() + 5.0
        while not any(name == "rpc_frame_errors_total"
                      for (name, _), _ in GLOBAL.snapshot().items()):
            assert time.monotonic() < deadline, "frame error never counted"
            time.sleep(0.01)

        run(server.stop(drain=True))  # GOAWAY both roles
        run(svc.stop(drain=True))
        assert server.frames_clean
    finally:
        cli.close()
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5.0)
        loop.close()

    text = GLOBAL.prometheus_text()
    for fam in EXPECTED_RPC_FAMILIES:
        assert fam in text, f"rpc family silent: {fam}"
    assert "# TYPE rpc_connections_active gauge" in text
    assert "# TYPE rpc_call_seconds histogram" in text
    assert "# HELP rpc_frame_errors_total" in text
    # batch decode is timed per format, and the DRR drain ledger counts
    # every row by tenant tms id
    assert 'fmt="columnar"' in text
    assert "serve_tenant_drains_total" in text
    assert "# TYPE rpc_decode_seconds histogram" in text
    # the C10k families export typed + help'd even when idle (loop
    # gauges and shed counters are pre-touched at server start); the
    # v4 round-trips above move real RESULT_BATCH frames both ways
    assert "# TYPE rpc_loops gauge" in text
    assert "# TYPE rpc_conns gauge" in text
    assert "# HELP rpc_accept_shed_total" in text
    assert 'rpc_accept_shed_total{reason="emfile"} 0' in text


# prover/ device proof synthesis families (PR: tpu-side prover) — stable
# interface; the synthesis path itself is covered in tests/test_prover.py
# and tests/test_prover_parity.py
EXPECTED_PROVER_FAMILIES = (
    "prover_proofs_total",
    "prover_rows_total",
    "prover_pad_rows_total",
    "prover_chunks_total",
    "prover_synthesize_seconds",
    "prover_corpus_proofs_total",
)


def test_prover_families_export():
    """The prover metric write path (the same helpers prove() calls per
    chunk) plus one host-source corpus generation light every prover_*
    family in a single exposition — without a device compile."""
    from fabric_token_sdk_tpu.crypto import setup
    from fabric_token_sdk_tpu.harness.corpus import ProofCorpus
    from fabric_token_sdk_tpu.prover import range as prover_range

    GLOBAL.reset()
    # production write path: one padded chunk (4 slots, 3 live rows)
    prover_range._observe_chunk("16", rows=4, live_rows=3, seconds=0.01)
    prover_range._observe_proofs("16", count=3, forged=False)
    prover_range._observe_proofs("16", count=1, forged=True)
    # corpus provenance counter: a tiny host-source corpus with one
    # forged row (forge_every=3 -> index 2)
    pp = setup.setup(4)
    entries = ProofCorpus(pp, source="host", seed=5,
                          forge_every=3).generate(3)
    assert [e.forged for e in entries] == [False, False, True]

    text = GLOBAL.prometheus_text()
    for fam in EXPECTED_PROVER_FAMILIES:
        assert fam in text, f"prover family silent: {fam}"
    assert "# TYPE prover_synthesize_seconds histogram" in text
    assert re.search(r'prover_pad_rows_total\{[^}]*bits="16"', text)
    assert re.search(
        r'prover_proofs_total\{[^}]*forged="true"[^}]*\} 1\.0', text)
    assert re.search(
        r'prover_corpus_proofs_total\{[^}]*source="host"', text)
