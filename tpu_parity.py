"""Device-vs-host parity spot check for the field/EC kernel layer.

Run ON THE REAL CHIP after touching ops/field.py or ops/ec.py (the MXU
truncation class of bug passes on CPU and fails only on TPU — see
.claude/skills/verify/SKILL.md). Checks mont_mul (incl. the byte-plane
reduction + int8 nibble constant products), complete adds, windowed MSM
and fixed-base gather against the pure-Python host oracle on random
inputs. Exits non-zero on any mismatch.
"""

import sys

from fabric_token_sdk_tpu.utils.jaxcfg import configure_jax_cache

configure_jax_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fabric_token_sdk_tpu.crypto import bn254  # noqa: E402
from fabric_token_sdk_tpu.ops import ec, field as F, limbs as L  # noqa: E402

rng = np.random.default_rng(0xF1E1D)
FAILS = 0


def check(name, got, want):
    global FAILS
    ok = (np.asarray(got) == np.asarray(want)).all()
    print(f"  {name}: {'ok' if ok else 'MISMATCH'}")
    if not ok:
        FAILS += 1


def rand_fp(n):
    return [int.from_bytes(rng.bytes(31), "little") % bn254.P
            for _ in range(n)]


def host_affine_limbs(p):
    """Host G1 -> canonical affine limbs (2, 16); identity -> zeros."""
    if p is None:
        return np.zeros((2, L.NLIMBS), dtype=np.uint32)
    return np.stack([L.int_to_limbs(p.x), L.int_to_limbs(p.y)])


def main():
    print(f"backend={jax.devices()[0].platform}")
    B = 64

    # ---- mont_mul vs host
    a_int, b_int = rand_fp(B), rand_fp(B)
    R = 1 << 256
    a = jnp.asarray(np.stack([L.int_to_limbs(v) for v in a_int]))
    b = jnp.asarray(np.stack([L.int_to_limbs(v) for v in b_int]))
    mm = jax.jit(lambda x, y: F.mont_mul(x, y, F.FP))
    got = np.asarray(mm(a, b))
    want = np.stack([
        L.int_to_limbs(av * bv * pow(R, -1, bn254.P) % bn254.P)
        for av, bv in zip(a_int, b_int)])
    check("mont_mul(fp)", got, want)

    mmr = jax.jit(lambda x, y: F.mont_mul(x, y, F.FR))
    ar = [v % bn254.R for v in a_int]
    br = [v % bn254.R for v in b_int]
    a2 = jnp.asarray(np.stack([L.int_to_limbs(v) for v in ar]))
    b2 = jnp.asarray(np.stack([L.int_to_limbs(v) for v in br]))
    got = np.asarray(mmr(a2, b2))
    want = np.stack([
        L.int_to_limbs(av * bv * pow(R, -1, bn254.R) % bn254.R)
        for av, bv in zip(ar, br)])
    check("mont_mul(fr)", got, want)

    # ---- complete add vs host
    ks = [int.from_bytes(rng.bytes(31), "little") % bn254.R for _ in range(B)]
    pts = [bn254.g1_mul(bn254.G1_GENERATOR, k) for k in ks]
    qts = [bn254.g1_mul(bn254.G1_GENERATOR, k + 7) for k in ks]
    pd = jnp.asarray(L.points_to_projective_limbs(pts))
    qd = jnp.asarray(L.points_to_projective_limbs(qts))
    s = jax.jit(ec.add)(pd, qd)
    aff = np.asarray(jax.jit(ec.to_affine)(s))
    want_aff = np.stack([
        host_affine_limbs(bn254.g1_add(p, q))
        for p, q in zip(pts, qts)])
    check("ec.add + to_affine", aff, want_aff)

    # ---- windowed MSM vs host
    T = 8
    msm_pts = [[bn254.g1_mul(bn254.G1_GENERATOR, 3 + i * T + t)
                for t in range(T)] for i in range(4)]
    msm_sc = [[int.from_bytes(rng.bytes(31), "little") % bn254.R
               for _ in range(T)] for _ in range(4)]
    dpts = jnp.asarray(np.stack(
        [L.points_to_projective_limbs(row) for row in msm_pts]))
    dsc = jnp.asarray(np.stack(
        [L.scalars_to_limbs(row) for row in msm_sc]))
    out = np.asarray(jax.jit(ec.to_affine)(jax.jit(ec.msm_windowed)(dpts, dsc)))
    want = np.stack([
        host_affine_limbs(bn254.msm(prow, srow))
        for prow, srow in zip(msm_pts, msm_sc)])
    check("msm_windowed", out, want)

    # ---- fixed-base gather vs host
    gens = [bn254.g1_mul(bn254.G1_GENERATOR, 11 + t) for t in range(4)]
    tables = jax.jit(ec.fixed_base_planes)(
        jnp.asarray(L.points_to_projective_limbs(gens)))
    sc = [[int.from_bytes(rng.bytes(31), "little") % bn254.R
           for _ in range(4)] for _ in range(3)]
    dsc = jnp.asarray(np.stack([L.scalars_to_limbs(row) for row in sc]))
    got = np.asarray(jax.jit(ec.to_affine)(
        jax.jit(ec.fixed_base_gather)(tables, dsc)))
    want = np.stack([
        np.stack([host_affine_limbs(bn254.g1_mul(g, s))
                  for g, s in zip(gens, row)]) for row in sc])
    check("fixed_base_gather", got, want)

    print("PARITY PASS" if FAILS == 0 else f"PARITY FAIL ({FAILS})")
    return 1 if FAILS else 0


if __name__ == "__main__":
    sys.exit(main())
