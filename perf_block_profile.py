"""Phase profile of ZKVerifier.verify_block at bench config-3 shapes.

Times each stage of the block path separately (deserialize, sigma device
pass, point adjustment, range batch) to locate the gap between the
679 proofs/s block number and the 1,948 proofs/s pure-range headline.
Run on the chip: python perf_block_profile.py
"""
import pickle
import sys
import time
from pathlib import Path

import numpy as np

BENCH_DIR = Path(__file__).parent / "benchdata"
BIT_LENGTH = 64
BATCH = 1024

from fabric_token_sdk_tpu.core.zkatdlog.verifier import ZKVerifier
from fabric_token_sdk_tpu.core.zkatdlog import verifier as vmod
from fabric_token_sdk_tpu.crypto import setup, transfer_proof, issue_proof
from fabric_token_sdk_tpu.models.adjust import adjust_points


def main():
    pp = setup.PublicParams.deserialize((BENCH_DIR / "pp.json").read_bytes())
    blob = pickle.loads((BENCH_DIR / f"block_{BIT_LENGTH}.pkl").read_bytes())
    base_t, base_i = blob["transfers"], blob["issues"]
    slice_t = (base_t * (BATCH // 4 // len(base_t) + 1))[:BATCH // 4]
    slice_i = (base_i * (BATCH // 4 // len(base_i) + 1))[:BATCH // 4]
    zk = ZKVerifier(pp, device=True)

    # warm-up (compiles everything)
    t0 = time.perf_counter()
    t_ok, i_ok = zk.verify_block(slice_t, slice_i)
    assert t_ok.all() and i_ok.all()
    print(f"warm-up {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    for rep in range(3):
        t0 = time.perf_counter()
        t_proofs = {k: transfer_proof.TransferProof.deserialize(raw)
                    for k, (raw, _, _) in enumerate(slice_t)}
        i_proofs = {k: issue_proof.IssueProof.deserialize(raw)
                    for k, (raw, _) in enumerate(slice_i)}
        t1 = time.perf_counter()
        ts_items = [(t_proofs[k].type_and_sum, slice_t[k][1], slice_t[k][2])
                    for k in sorted(t_proofs)]
        st_items = [i_proofs[k].same_type for k in sorted(i_proofs)]
        ts_acc = zk._sigma.verify_type_and_sum(ts_items)
        st_acc = zk._sigma.verify_same_type(st_items)
        assert all(ts_acc) and all(st_acc)
        t2 = time.perf_counter()
        range_proofs, raw_pts, raw_ctts = [], [], []
        for k in sorted(t_proofs):
            p, (_, ins, outs) = t_proofs[k], slice_t[k]
            ctt = p.type_and_sum.commitment_to_type
            for o, rpp in zip(outs, p.range_correctness.proofs):
                range_proofs.append(rpp)
                raw_pts.append(o)
                raw_ctts.append(ctt)
        for k in sorted(i_proofs):
            p, (_, coms) = i_proofs[k], slice_i[k]
            ctt = p.same_type.commitment_to_type
            for c, rpp in zip(coms, p.range_correctness.proofs):
                range_proofs.append(rpp)
                raw_pts.append(c)
                raw_ctts.append(ctt)
        t3 = time.perf_counter()
        range_coms = adjust_points(raw_pts, raw_ctts)
        t4 = time.perf_counter()
        accepts = zk._range.verify(range_proofs, range_coms)
        assert accepts.all()
        t5 = time.perf_counter()
        print(f"rep{rep}: total {t5-t0:.3f}s | deser {t1-t0:.3f} "
              f"sigma {t2-t1:.3f} assemble {t3-t2:.3f} "
              f"adjust {t4-t3:.3f} range[{len(range_proofs)}] {t5-t4:.3f}")


if __name__ == "__main__":
    main()
